"""Autoregressive generation with a KV cache.

The reference's "inference" was a timed validation pass over MNIST
(mnist_single.py:124-134) — classification only. The LM family here
gets the real thing: prefill the prompt in one pass, then decode one
token per step against per-layer KV caches ([B, max_len, H, Dh],
static shapes, updated in place via dynamic_update_slice), the whole
loop a single ``lax.scan`` under jit — no per-token host round-trips,
no recompilation, O(L) attention per new token instead of O(L^2)
re-forwarding.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.observe.registry import emit_event

# --- compiled-program cache accounting ---------------------------------
#
# Every jitted program here is built by an lru_cache'd factory; a MISS
# means a fresh trace + XLA compile (seconds to minutes), a HIT reuses
# the executable. Retrace storms — e.g. a caller cycling max_new_tokens
# or sampler knobs per request — show up as a climbing miss count, so
# the counts are queryable (compile_cache_stats) and each miss emits a
# "compile_cache" record through the active observe registry.

_compile_events = {"hits": 0, "misses": 0}


def compile_cache_stats() -> dict:
    """Cumulative compiled-program cache hits/misses (process-wide,
    all program factories in this module plus serve/engine.py's
    bucketed prefill)."""
    return dict(_compile_events)


def lookup_program(factory, *key):
    """Fetch ``factory(*key)`` counting lru_cache hits/misses; a miss
    (a fresh trace+compile) also emits a ``compile_cache`` observe
    record naming the factory, so retrace storms are visible in the
    run's JSONL instead of only as mysterious wall time."""
    before = factory.cache_info().misses
    fn = factory(*key)
    if factory.cache_info().misses > before:
        _compile_events["misses"] += 1
        emit_event("compile_cache", program=factory.__name__,
                   result="miss", **_compile_events)
    else:
        _compile_events["hits"] += 1
    return fn


def prefill_cache(model, params, prompt: jax.Array,
                  positions: Optional[jax.Array] = None):
    """One forward pass over ``prompt`` [B, P] that populates every
    layer's KV cache — THE prefill, shared by greedy decoding, beam
    search, and the serving engine's bucketed prefill programs
    (serve/engine.py). Returns (logits [B, P, V], cache pytree).

    ``positions`` defaults to arange(P) (a fresh cache); pass explicit
    positions to prefill at an offset."""
    if positions is None:
        positions = jnp.arange(prompt.shape[1])[None, :]
    logits, state = model.apply(
        {"params": params}, prompt, decode=True,
        positions=positions, mutable=["cache"])
    return logits, state["cache"]


def decode_token(model, params, cache, tok: jax.Array,
                 positions: jax.Array):
    """One single-token decode step against the cache — THE decode
    step, shared by greedy decoding, beam search, and the serving
    engine. ``tok`` [B] int32; ``positions`` [B] (per-row cache
    depths — the serving engine's slots differ) or [1] (every row in
    lockstep). Returns (last-position logits [B, V], updated cache)."""
    pos = jnp.asarray(positions, jnp.int32)
    if pos.ndim == 0:
        pos = pos[None]
    logits, state = model.apply(
        {"params": params, "cache": cache}, tok[:, None], decode=True,
        positions=pos[:, None], mutable=["cache"])
    return logits[:, -1, :], state["cache"]


def _filter_logits(logits: jax.Array, top_k: int, top_p: float
                   ) -> jax.Array:
    """Mask logits outside the top-k / nucleus (top-p) candidate set.

    Both filters are static-shape TPU-friendly: top-k keeps the k-th
    value as a threshold (no gather/scatter of dynamic extent); top-p
    sorts once, finds the smallest prefix with cumulative probability
    >= p, and thresholds on that boundary logit. Filtered entries go to
    -inf so ``jax.random.categorical`` never picks them.
    """
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the minimal prefix whose mass reaches p (always >= 1
        # token: the first prefix that crosses p is included).
        keep = cum - probs < top_p
        # Smallest kept logit bounds the nucleus from below.
        boundary = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
            keepdims=True)
        logits = jnp.where(logits < boundary, -jnp.inf, logits)
    return logits


@functools.lru_cache(maxsize=32)
def _compiled(model, max_new_tokens: int, temperature: float,
              top_k: int, top_p: float):
    """One jitted prefill+decode program per (model, N, sampler knobs).

    Cached so repeat generate() calls reuse the compiled executable
    (jit's cache is keyed on the function object — a closure rebuilt
    per call would retrace every time). Flax modules are frozen
    dataclasses, hence hashable cache keys.
    """

    def run(params, prompt, key):
        P = prompt.shape[1]
        # Prefill: one pass over the prompt populates every layer cache.
        logits, cache = prefill_cache(model, params, prompt)

        def pick(last, key):
            if temperature == 0.0:
                return jnp.argmax(last, axis=-1).astype(jnp.int32)
            last = _filter_logits(last / temperature, top_k, top_p)
            return jax.random.categorical(
                key, last, axis=-1).astype(jnp.int32)

        def step(carry, _):
            cache, tok, pos, key = carry
            key, sub = jax.random.split(key)
            last, cache = decode_token(model, params, cache, tok,
                                       pos[None])
            nxt = pick(last, sub)
            return (cache, nxt, pos + 1, key), nxt

        key, sub = jax.random.split(key)
        first = pick(logits[:, -1, :], sub)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, first, jnp.asarray(P, jnp.int32), key),
            None, length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], toks.T], axis=1)

    # The registry name carries the FULL lru key beyond the model:
    # distinct sampler knobs are distinct resident executables, and
    # aliasing them under one name would make the HBM budget rollup
    # undercount what actually stays loaded.
    name = f"generate_n{max_new_tokens}"
    if temperature != 0.0:
        name += f"_t{temperature:g}_k{top_k}_p{top_p:g}"
    return observe_device.instrument_jit(name, run)


def generate(model, params, prompt: jax.Array, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Continue ``prompt`` [B, P] by ``max_new_tokens`` greedy
    (temperature 0) or sampled tokens. Returns [B, max_new_tokens].

    ``model`` is a causal TransformerLM (models/transformer.py). The
    mesh's seq axis must be 1 (single-token steps can't be
    seq-sharded); batch stays sharded over "data" as usual.

    Sampling knobs (active only with ``temperature > 0``):
    ``top_k > 0`` restricts to the k highest-logit tokens; ``top_p <
    1.0`` restricts to the smallest nucleus whose probability mass
    reaches p (Holtzman et al.); both may be combined (k first, then p
    over the survivors).
    """
    cfg = model.cfg
    if not cfg.causal:
        raise ValueError("generate() needs a causal model")
    B, P = prompt.shape
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt {P} + {max_new_tokens} new > max_len {cfg.max_len}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    key = key if key is not None else jax.random.key(0)
    if temperature == 0.0:
        # Greedy ignores the sampler knobs — normalize them so the
        # compile cache isn't fragmented by values the program never
        # reads.
        top_k, top_p = 0, 1.0
    return lookup_program(_compiled, model, max_new_tokens, temperature,
                          top_k, float(top_p))(params, prompt, key)


@functools.lru_cache(maxsize=32)
def _compiled_beam(model, max_new_tokens: int, num_beams: int,
                   length_penalty: float, eos_id: int):
    """One jitted beam-search program per (model, N, K, penalty, eos).

    TPU-native shape discipline: beams ride a flat [B*K] batch through
    the SAME cached decode path greedy uses (prefill once per beam,
    one token per step under lax.scan, static shapes everywhere); the
    per-step reindex after top-k is a batched gather of the cache
    pytree along the flat beam dim.
    """

    def run(params, prompt):
        B, P = prompt.shape
        K = num_beams
        V = model.cfg.vocab_size
        NEG = jnp.asarray(-1e30, jnp.float32)

        # Prefill ONCE per batch row, then tile the cache to [B*K]:
        # the K beam copies are byte-identical, so repeating the
        # cache leaves costs 1/K of the prompt-dominant prefill
        # FLOPs and HBM traffic that repeating the PROMPT would.
        logits, pre = prefill_cache(model, params, prompt)
        cache = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, K, axis=0)
            if getattr(c, "ndim", 0) and c.shape[0] == B else c,
            pre)
        logp0 = jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32))      # [B, V]
        # First expansion: B x top-K over the vocab seeds the beams.
        scores, tok0 = jax.lax.top_k(logp0, K)         # [B, K]
        toks0 = tok0.reshape(B * K).astype(jnp.int32)
        alive0 = (toks0.reshape(B, K) != eos_id) if eos_id >= 0 else \
            jnp.ones((B, K), bool)

        def step(carry, i):
            cache, scores, alive, tok = carry
            # Fed token sits AT position P + i.
            last, cache = decode_token(model, params, cache, tok,
                                       jnp.full((1,), P + i))
            logp = jax.nn.log_softmax(
                last.astype(jnp.float32)).reshape(B, K, V)
            # Finished beams emit ONLY eos at zero cost, so they keep
            # their score and stay comparable with live beams.
            if eos_id >= 0:
                frozen = jnp.full((V,), NEG).at[eos_id].set(0.0)
                logp = jnp.where(alive[..., None], logp, frozen)
            cand = scores[..., None] + logp            # [B, K, V]
            flat_scores, flat_idx = jax.lax.top_k(
                cand.reshape(B, K * V), K)             # [B, K]
            beam_idx = flat_idx // V                   # [B, K]
            new_tok = (flat_idx % V).astype(jnp.int32)
            gather = (jnp.arange(B)[:, None] * K
                      + beam_idx).reshape(B * K)       # flat reindex
            cache = jax.tree_util.tree_map(
                lambda c: jnp.take(c, gather, axis=0)
                if getattr(c, "ndim", 0) and c.shape[0] == B * K else c,
                cache)
            alive = jnp.take_along_axis(alive, beam_idx, axis=1)
            if eos_id >= 0:
                alive = jnp.logical_and(alive, new_tok != eos_id)
            return ((cache, flat_scores, alive,
                     new_tok.reshape(B * K)),
                    (new_tok, beam_idx))

        (_, scores, _, _), (toks, parents) = jax.lax.scan(
            step, (cache, scores, alive0, toks0),
            jnp.arange(max_new_tokens - 1))

        # Backtrack parents to materialize each beam's token path.
        def back(carry, sp):
            ptr = carry                                # [B, K]
            t, par = sp
            tok_here = jnp.take_along_axis(t, ptr, axis=1)
            ptr = jnp.take_along_axis(par, ptr, axis=1)
            return ptr, tok_here

        ptr0 = jnp.tile(jnp.arange(K)[None], (B, 1))
        ptr, rev = jax.lax.scan(back, ptr0, (toks, parents),
                                reverse=True)
        first = jnp.take_along_axis(tok0, ptr, axis=1) # [B, K]
        seq = jnp.concatenate([first[:, :, None],
                               jnp.moveaxis(rev, 0, 2)], axis=2)
        # Length-normalized ranking (GNMT-style): finished beams are
        # shorter than max_new_tokens only when eos fired; count real
        # tokens up to and including the first eos.
        if eos_id >= 0:
            is_eos = seq == eos_id
            any_eos = is_eos.any(axis=2)
            first_eos = jnp.argmax(is_eos, axis=2)
            length = jnp.where(any_eos, first_eos + 1, seq.shape[2])
        else:
            length = jnp.full((B, K), seq.shape[2])
        norm = scores / (length.astype(jnp.float32) ** length_penalty)
        order = jnp.argsort(-norm, axis=1)
        seq = jnp.take_along_axis(seq, order[:, :, None], axis=1)
        return seq, jnp.take_along_axis(norm, order, axis=1)

    return observe_device.instrument_jit(
        f"beam_search_n{max_new_tokens}_k{num_beams}"
        f"_lp{length_penalty:g}_eos{eos_id}", run)


def beam_search(model, params, prompt: jax.Array, max_new_tokens: int,
                *, num_beams: int = 4, length_penalty: float = 1.0,
                eos_id: Optional[int] = None):
    """Beam-search continuation of ``prompt`` [B, P]: returns
    (sequences [B, num_beams, max_new_tokens], scores [B, num_beams]),
    beams sorted best-first by length-normalized log-probability
    (GNMT ``length_penalty``; 0 disables normalization).

    ``eos_id``: beams that emit it freeze (score kept, eos-padded) —
    the standard early-finish semantics; None runs every beam to the
    full budget. num_beams=1 is exactly greedy decoding (tested).
    Same requirements as ``generate`` (causal model, mesh seq 1)."""
    cfg = model.cfg
    if not cfg.causal:
        raise ValueError("beam_search() needs a causal model")
    B, P = prompt.shape
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt {P} + {max_new_tokens} new > max_len {cfg.max_len}")
    if num_beams < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if num_beams > cfg.vocab_size:
        raise ValueError(
            f"num_beams {num_beams} > vocab_size {cfg.vocab_size} "
            "(the first expansion is a top-k over the vocabulary)")
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab_size:
        raise ValueError(f"eos_id {eos_id} outside vocab "
                         f"[0, {cfg.vocab_size})")
    return lookup_program(_compiled_beam, model, max_new_tokens,
                          num_beams, float(length_penalty),
                          -1 if eos_id is None else int(eos_id))(
        params, prompt)
