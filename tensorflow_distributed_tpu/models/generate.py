"""Autoregressive generation with a KV cache.

The reference's "inference" was a timed validation pass over MNIST
(mnist_single.py:124-134) — classification only. The LM family here
gets the real thing: prefill the prompt in one pass, then decode one
token per step against per-layer KV caches ([B, max_len, H, Dh],
static shapes, updated in place via dynamic_update_slice), the whole
loop a single ``lax.scan`` under jit — no per-token host round-trips,
no recompilation, O(L) attention per new token instead of O(L^2)
re-forwarding.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _filter_logits(logits: jax.Array, top_k: int, top_p: float
                   ) -> jax.Array:
    """Mask logits outside the top-k / nucleus (top-p) candidate set.

    Both filters are static-shape TPU-friendly: top-k keeps the k-th
    value as a threshold (no gather/scatter of dynamic extent); top-p
    sorts once, finds the smallest prefix with cumulative probability
    >= p, and thresholds on that boundary logit. Filtered entries go to
    -inf so ``jax.random.categorical`` never picks them.
    """
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the minimal prefix whose mass reaches p (always >= 1
        # token: the first prefix that crosses p is included).
        keep = cum - probs < top_p
        # Smallest kept logit bounds the nucleus from below.
        boundary = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
            keepdims=True)
        logits = jnp.where(logits < boundary, -jnp.inf, logits)
    return logits


@functools.lru_cache(maxsize=32)
def _compiled(model, max_new_tokens: int, temperature: float,
              top_k: int, top_p: float):
    """One jitted prefill+decode program per (model, N, sampler knobs).

    Cached so repeat generate() calls reuse the compiled executable
    (jit's cache is keyed on the function object — a closure rebuilt
    per call would retrace every time). Flax modules are frozen
    dataclasses, hence hashable cache keys.
    """

    @jax.jit
    def run(params, prompt, key):
        P = prompt.shape[1]
        # Prefill: one pass over the prompt populates every layer cache.
        logits, state = model.apply(
            {"params": params}, prompt, decode=True,
            positions=jnp.arange(P)[None, :], mutable=["cache"])
        cache = state["cache"]

        def pick(logits, key):
            last = logits[:, -1, :]
            if temperature == 0.0:
                return jnp.argmax(last, axis=-1).astype(jnp.int32)
            last = _filter_logits(last / temperature, top_k, top_p)
            return jax.random.categorical(
                key, last, axis=-1).astype(jnp.int32)

        def step(carry, _):
            cache, tok, pos, key = carry
            key, sub = jax.random.split(key)
            logits, state = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                decode=True, positions=pos[None, None],
                mutable=["cache"])
            nxt = pick(logits, sub)
            return (state["cache"], nxt, pos + 1, key), nxt

        key, sub = jax.random.split(key)
        first = pick(logits, sub)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, first, jnp.asarray(P, jnp.int32), key),
            None, length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], toks.T], axis=1)

    return run


def generate(model, params, prompt: jax.Array, max_new_tokens: int, *,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             key: Optional[jax.Array] = None) -> jax.Array:
    """Continue ``prompt`` [B, P] by ``max_new_tokens`` greedy
    (temperature 0) or sampled tokens. Returns [B, max_new_tokens].

    ``model`` is a causal TransformerLM (models/transformer.py). The
    mesh's seq axis must be 1 (single-token steps can't be
    seq-sharded); batch stays sharded over "data" as usual.

    Sampling knobs (active only with ``temperature > 0``):
    ``top_k > 0`` restricts to the k highest-logit tokens; ``top_p <
    1.0`` restricts to the smallest nucleus whose probability mass
    reaches p (Holtzman et al.); both may be combined (k first, then p
    over the survivors).
    """
    cfg = model.cfg
    if not cfg.causal:
        raise ValueError("generate() needs a causal model")
    B, P = prompt.shape
    if P + max_new_tokens > cfg.max_len:
        raise ValueError(
            f"prompt {P} + {max_new_tokens} new > max_len {cfg.max_len}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    key = key if key is not None else jax.random.key(0)
    if temperature == 0.0:
        # Greedy ignores the sampler knobs — normalize them so the
        # compile cache isn't fragmented by values the program never
        # reads.
        top_k, top_p = 0, 1.0
    return _compiled(model, max_new_tokens, temperature, top_k,
                     float(top_p))(params, prompt, key)
