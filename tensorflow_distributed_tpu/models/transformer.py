"""Transformer / BERT-base MLM (BASELINE.json stretch config), with
tensor- and sequence-parallel shardings. Implemented in a later
milestone of this round; importable now so the registry stays total."""

from __future__ import annotations


def bert_base_mlm(**kw):
    raise NotImplementedError("bert_mlm lands in a later milestone")
