"""Transformer encoder / BERT-style MLM with TP + SP shardings.

The BASELINE.json stretch config ("BERT-base MLM pretrain — prove the
ps->allreduce port generalizes past convnets"). The reference has no
sequence models (SURVEY.md §5), so this family is designed TPU-first
with no reference counterpart to mirror:

- **Tensor parallelism** (mesh "model" axis), Megatron-style: attention
  heads and MLP hidden dim are sharded via ``nn.with_partitioning``
  metadata; XLA's SPMD partitioner inserts the two allreduces per block
  (after attention out-proj and MLP down-proj) — nobody writes them.
- **Sequence parallelism** (mesh "seq" axis): activations are sharded
  along the sequence dim end-to-end; attention runs as exact ring
  attention (parallel.ring_attention) with K,V blocks rotating over ICI
  via ppermute.
- bf16 compute / f32 params, f32 layernorm and softmax statistics.

Layout conventions (matched to ``parallel.sharding.param_sharding``):
    qkv kernel   [d_model, 3, H, Dh]   P(None, None, "model", None)
    out kernel   [H, Dh, d_model]      P("model", None, None)
    mlp up       [d_model, d_ff]       P(None, "model")
    mlp down     [d_ff, d_model]       P("model", None)
    embeddings   [vocab, d_model]      replicated by default;
                                       P("model", None) with shard_vocab
                                       (Megatron vocab-parallel table)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflow_distributed_tpu.parallel.mesh import (
    AXIS_DATA, AXIS_MODEL, AXIS_SEQ)
from tensorflow_distributed_tpu.ops.flash_attention import attention
from tensorflow_distributed_tpu.parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 30522          # BERT-base WordPiece vocab
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_len: int = 512
    dropout_rate: float = 0.1
    compute_dtype: Any = jnp.bfloat16
    remat: bool = False              # jax.checkpoint each block
    # "full": save only block boundaries (max recompute, min HBM);
    # "dots": jax.checkpoint_policies.dots_saveable — keep matmul
    # outputs, recompute the cheap elementwise tail (the usual sweet
    # spot on TPU where HBM bandwidth, not FLOPs, binds).
    remat_policy: str = "full"
    causal: bool = False             # autoregressive (GPT) vs bidirectional
    # TP partition metadata on kernels. Disabled by the pipelined
    # variant: flax's DenseGeneral validates params at apply by
    # eval_shape-ing its init, which flattens multi-dim kernels to 2D
    # and then applies the 4-axis partition constraint to the flat
    # value — a rank mismatch that only errors inside a manual-axes
    # shard_map (the pipeline's). With mesh model == 1 the metadata is
    # meaningless there anyway.
    tp_partitioning: bool = True
    # Pallas flash attention on TPU. Works in the pipelined variant
    # too: the dispatcher (ops.flash_attention.attention) nests a
    # shard_map over the remaining auto axes inside the pipe-manual
    # region, so the Mosaic call sees fully-manual axes ("Mosaic
    # kernels cannot be automatically partitioned" otherwise).
    use_flash: bool = True
    # Sliding-window attention (Mistral-style): each token attends
    # to the last `attn_window` positions only (0 = full causal).
    # Causal families only; rides the flash kernel's block-skip so
    # compute is O(L * W) not O(L^2 / 2), and the decode path masks
    # cache entries older than the window. Long-context note: at
    # W << L this replaces ring attention (mesh.seq must be 1 —
    # windowing the zigzag schedule is not implemented).
    attn_window: int = 0
    # KV-cache storage for decode: "none" (cache in compute dtype)
    # or "int8" (per-(token, head) absmax quantization; the attend
    # consumes int8 directly via exact scale-adjusted dots, so the
    # full-cache HBM read — decode's dominant traffic — halves vs
    # bf16). Composes with GQA: n_kv_heads narrows the cache,
    # int8 thins it.
    kv_cache_quant: str = "none"  # none | int8
    # Paged KV cache for decode (serve/paging): > 0 replaces the
    # per-row [B, max_len, ...] cache with a shared page pool
    # [kv_num_pages, kv_page_size, ...] addressed through a per-row
    # ``page_table`` ([B, max_pages] int32, max_pages * page_size ==
    # max_len). Writes scatter each token's K/V into
    # (table[pos // page_size], pos % page_size); reads gather the
    # row's pages back into the SAME [B, max_len, ...] logical layout
    # the dense path attends — the attend itself (masking, scale
    # handling, numerics) is shared, so paged and dense decode produce
    # identical math over identical cache bytes. 0 = dense (default;
    # generate()/beam and the plain serve engine never pay paging).
    kv_page_size: int = 0
    # Physical pages in the pool (required > 0 when kv_page_size > 0;
    # page 0 is the serve engine's write-off page for freed rows).
    kv_num_pages: int = 0
    # Mixture-of-Experts: 0 = dense MLP; > 0 replaces every block's MLP
    # with an expert-parallel MoeMlp (models/moe.py).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # Routing-group length (0 = whole sequence); see
    # models/moe.py's scale-envelope note.
    moe_group_len: int = 0
    # Token-movement formulation: "dense" (GShard one-hot
    # einsums) or "scatter" (slot scatter/gather); models/moe.py.
    moe_dispatch: str = "dense"
    # Mesh axis the expert dim shards over: "model" (the default — EP
    # composes with TP's axis) or the dedicated "expert" axis
    # (MeshConfig.expert). moe_lm auto-selects "expert" when the mesh
    # has one.
    moe_expert_axis: str = AXIS_MODEL
    # Position encoding: "learned" (additive embedding, the GPT-2/BERT
    # scheme) or "rope" (rotary, applied to q/k per layer — relative
    # positions, the modern long-context default). RoPE composes with
    # flash/ring attention unchanged: rotation happens BEFORE the
    # kernel sees q/k, and it's elementwise along the sequence dim so
    # seq-sharding partitions it like any other activation op.
    pos_emb: str = "learned"  # learned | rope
    rope_theta: float = 10000.0
    # Share the input embedding as the output projection (GPT-2 ties
    # them): logits = x @ tok_emb.T via nn.Embed.attend. Saves a
    # [d_model, vocab] matrix and its optimizer slots; the [MASK]
    # sentinel row (extra_vocab) is sliced off the logits.
    tie_embeddings: bool = False
    # Grouped-query attention: number of K/V heads (None = n_heads,
    # standard MHA; 1 = MQA). Q keeps n_heads; K/V project to
    # n_kv_heads and broadcast to the query heads right before each
    # attend, so the flash/ring kernels and the XLA oracle are
    # untouched — what shrinks is the KV projection params and,
    # crucially, the decode cache: [B, max_len, n_kv, Dh] instead of
    # [B, max_len, H, Dh] (the decode-bandwidth win GQA exists for).
    n_kv_heads: Optional[int] = None
    # MLP nonlinearity: "gelu" (GPT-2/BERT two-matrix MLP) or "swiglu"
    # (gated: silu(gate(x)) * up(x) -> down; the Llama-family MLP).
    mlp_variant: str = "gelu"  # gelu | swiglu
    # Shard the token-embedding table's vocab dim over the "model"
    # axis (Megatron's vocab-parallel embedding). At vocab 50257 the
    # table + its Adam slots are ~460 MB f32 per replica on GPT-2-small
    # — the knob that splits them across TP ranks. The untied lm_head
    # already shards vocab this way; this extends it to the input table
    # and the tied path (logits come out vocab-sharded; GSPMD inserts
    # the gather/reduce where the loss needs them). Requires
    # tp_partitioning (i.e. not the pipelined family).
    shard_vocab: bool = False
    # Block normalization: "layernorm" (mean+variance, bias+scale) or
    # "rmsnorm" (scale-only, no mean subtraction — cheaper and the
    # modern default). Both run in f32.
    norm: str = "layernorm"  # layernorm | rmsnorm
    # Model-health activation taps (--observe.health-taps): each block
    # sows the f32 RMS of its output into the transient "health"
    # collection; the train step folds it into the cadence-gated
    # per-layer health metrics (observe/health.py). Off by default —
    # a tap is one elementwise reduction per block per step, but it
    # also pins the residual stream as a live value, so it is a knob,
    # not a constant. Sown only when the "health" collection is
    # mutable (training forward passes), so eval/decode never pay it.
    health_taps: bool = False


def bert_base_config(**overrides) -> TransformerConfig:
    return dataclasses.replace(TransformerConfig(), **overrides)


def tiny_config(**overrides) -> TransformerConfig:
    """Small config for tests/CI: same code paths, toy scale."""
    base = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                             n_heads=4, d_ff=64, max_len=128,
                             dropout_rate=0.0, compute_dtype=jnp.float32)
    return dataclasses.replace(base, **overrides)


def resolve_remat_policy(name: str):
    """remat_policy name -> jax.checkpoint policy (the ONE mapping,
    shared by TransformerLM and PipelinedLM)."""
    if name == "dots":
        return jax.checkpoint_policies.dots_saveable
    if name == "full":
        return None
    raise ValueError(f"remat_policy {name!r}; have ('full', 'dots')")


def _dense_init():
    return nn.initializers.normal(stddev=0.02)  # BERT-style


def rope_rotate(x: jax.Array, positions: jax.Array,
                theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding (Su et al., RoFormer).

    x: [B, L, H, Dh] (Dh even), positions: [B, L] or [1, L] int.
    Rotates each (x[2i], x[2i+half]) pair by positions * theta^(-i/half)
    in f32 (angle precision matters at long context), returning x's
    dtype. The defining property — attention scores depend only on
    RELATIVE position — is pinned in tests/test_rope.py.
    """
    if x.shape[-1] % 2:
        raise ValueError(
            f"rope needs an even head dim, got Dh={x.shape[-1]}")
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [B,L,half]
    cos = jnp.cos(angles)[..., None, :]                        # [B,L,1,half]
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


def _auto_expert_axis(mesh, overrides) -> None:
    """Any MoE config on a mesh with a real dedicated "expert" axis
    defaults to sharding experts over it — otherwise wi/wo would name
    the size-1 "model" axis and the expert-axis device group would do
    fully redundant work with no warning."""
    if (overrides.get("moe_experts", 0) > 0 and mesh is not None
            and dict(mesh.shape).get("expert", 1) > 1):
        overrides.setdefault("moe_expert_axis", "expert")


def _auto_tp_partitioning(mesh, overrides) -> None:
    """Default TP metadata OFF when the mesh has no model axis to shard
    over: the annotations are meaningless at mesh.model == 1 (the
    pipelined variant already disables them for the same reason) and
    flax-version skew can make the boxed with_sharding_constraint
    reject them outright at init. shard_vocab keeps them (its vocab-
    parallel embedding requires the metadata), as does an explicit
    tp_partitioning override, and a mesh-less build keeps the factory
    default (pure metadata, nothing constrains it)."""
    if overrides.get("shard_vocab"):
        return
    if mesh is not None and dict(mesh.shape).get("model", 1) == 1:
        overrides.setdefault("tp_partitioning", False)



def _maybe_partitioned(cfg, names):
    """kernel_init with TP metadata, or plain when tp_partitioning=False
    (see TransformerConfig.tp_partitioning for why)."""
    init = _dense_init()
    return nn.with_partitioning(init, names) if cfg.tp_partitioning else init


class SelfAttention(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x: jax.Array, *, train: bool = False,
                 decode: bool = False,
                 positions: Optional[jax.Array] = None,
                 page_table: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        h, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
        # None AND 0 both mean MHA (TrainConfig uses 0 as its sentinel).
        nk = cfg.n_kv_heads or h
        if h % nk:
            raise ValueError(
                f"n_heads {h} not divisible by n_kv_heads {nk}")
        if nk == h:
            # Standard MHA: one fused projection (param tree unchanged
            # from before GQA existed — checkpoints stay loadable).
            qkv = nn.DenseGeneral(
                features=(3, h, dh), axis=-1, use_bias=True,
                kernel_init=_maybe_partitioned(
                    cfg, (None, None, AXIS_MODEL, None)),
                dtype=cfg.compute_dtype, name="qkv")(x)
            q, k, v = (qkv[..., 0, :, :], qkv[..., 1, :, :],
                       qkv[..., 2, :, :])
        else:
            q = nn.DenseGeneral(
                features=(h, dh), axis=-1, use_bias=True,
                kernel_init=_maybe_partitioned(cfg, (None, AXIS_MODEL, None)),
                dtype=cfg.compute_dtype, name="q")(x)
            # K/V kernels stay replicated: nk is typically smaller than
            # the TP axis, and the tensors are small by construction.
            kv = nn.DenseGeneral(
                features=(2, nk, dh), axis=-1, use_bias=True,
                kernel_init=_dense_init(),
                dtype=cfg.compute_dtype, name="kv")(x)
            k, v = kv[..., 0, :, :], kv[..., 1, :, :]

        def widen(t):
            """[B, L, nk, Dh] -> [B, L, H, Dh] for the attend."""
            return (t if nk == h else
                    jnp.repeat(t, h // nk, axis=2))
        if cfg.pos_emb == "rope":
            if positions is None:
                raise ValueError("pos_emb='rope' needs positions")
            # Rotate BEFORE caching/dispatch: cached keys are stored
            # rotated, so decode attends rotated-q against rotated-k
            # with no per-step re-rotation of the cache.
            q = rope_rotate(q, positions, cfg.rope_theta)
            k = rope_rotate(k, positions, cfg.rope_theta)
        if decode:
            # KV-cache incremental decoding: stash k/v at each row's
            # position, attend q (the L new tokens) against the whole
            # cache with a position mask. Static shapes throughout —
            # the cache is always [B, max_len, H, Dh]. POSITIONS are
            # the authority on where writes land (they already had to
            # be per-step correct for RoPE and the mask): rows may sit
            # at DIFFERENT depths — the serving engine's slots
            # (serve/engine.py) decode a [num_slots] batch whose
            # requests joined at different times — so writes are
            # per-row dynamic_update_slices vmapped over the batch.
            # A [1, L] positions array broadcasts to the whole batch
            # (the generate()/beam path, every row in lockstep).
            if not cfg.causal:
                raise ValueError("decode=True needs a causal config")
            B, L = x.shape[0], x.shape[1]
            from tensorflow_distributed_tpu.parallel.ring_attention import (
                full_attention)
            quant = cfg.kv_cache_quant == "int8"
            cache_dt = jnp.int8 if quant else k.dtype
            paged = cfg.kv_page_size > 0
            if paged:
                # Paged layout (serve/paging): the cache is a POOL of
                # fixed-size pages shared by every row; ``page_table``
                # maps each row's logical pages to physical ones. The
                # write/read addressing below is the only paged code —
                # masking and the attend are the dense path's.
                if page_table is None:
                    raise ValueError(
                        "kv_page_size > 0 needs a page_table "
                        "([B, max_pages] int32)")
                npages, psz = cfg.kv_num_pages, cfg.kv_page_size
                if npages < 2:
                    raise ValueError(
                        f"kv_num_pages must be >= 2 (page 0 is the "
                        f"write-off page), got {npages}")
                if page_table.shape != (B, cfg.max_len // psz) or \
                        cfg.max_len % psz:
                    raise ValueError(
                        f"page_table {page_table.shape} must be "
                        f"[B={B}, max_len/page_size="
                        f"{cfg.max_len}/{psz}] (max_len must divide "
                        f"evenly into pages)")
                kv_shape = (npages, psz, nk, dh)
                sc_shape = (npages, psz, nk)
            else:
                kv_shape = (B, cfg.max_len, nk, dh)
                sc_shape = (B, cfg.max_len, nk)
            ck = self.variable("cache", "key", jnp.zeros,
                               kv_shape, cache_dt)
            cv = self.variable("cache", "value", jnp.zeros,
                               kv_shape, cache_dt)
            if quant:
                # Per-(token, head) absmax scales — the standard
                # inference quantization grain: one f32 per cached
                # row, 2*dh fewer bytes than the row it scales.
                cks = self.variable("cache", "key_scale", jnp.zeros,
                                    sc_shape, jnp.float32)
                cvs = self.variable("cache", "value_scale", jnp.zeros,
                                    sc_shape, jnp.float32)
            ci = self.variable("cache", "index",
                               lambda: jnp.zeros((), jnp.int32))
            pos = positions.astype(jnp.int32)       # [1 | B, L]
            # Each row's L new tokens are contiguous from its first
            # position (prefill: arange; decode: a single token).
            start = jnp.broadcast_to(pos[:, :1], (B, 1))[:, 0]  # [B]

            def _row_put(buf, new, s):
                return jax.lax.dynamic_update_slice(
                    buf, new, (s,) + (0,) * (new.ndim - 1))

            if paged:
                # Scatter each token's K/V into its physical page:
                # pid = table[pos // page_size], off = pos % page_size.
                # Positions stay the single authority on depth — the
                # table only relocates where a position's bytes live.
                # Bucket-padding positions PAST the cache end (a tail
                # prefill at offset m may pad to m + bucket > max_len;
                # a dense row had max_len of slack for that garbage)
                # park in the write-off page 0, which no table ever
                # exposes to an unmasked column.
                posb = jnp.broadcast_to(pos, (B, L))
                lp = jnp.minimum(posb // psz, page_table.shape[1] - 1)
                pid = jnp.take_along_axis(
                    page_table.astype(jnp.int32), lp, axis=1)
                pid = jnp.where(posb < cfg.max_len, pid, 0)
                off = posb % psz                              # [B, L]

                def put(buf, new, _start):
                    return buf.at[pid, off].set(new)
            else:
                put = jax.vmap(_row_put)

            def q8(x):
                scale = jnp.maximum(
                    jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
                    / 127.0, 1e-8)                     # [B, L, nk]
                rounded = jnp.round(x.astype(jnp.float32)
                                    / scale[..., None])
                return (jnp.clip(rounded, -127, 127).astype(jnp.int8),
                        scale)

            if quant:
                k8, ks = q8(k)
                v8, vs = q8(v)
                ck.value = put(ck.value, k8, start)
                cv.value = put(cv.value, v8, start)
                cks.value = put(cks.value, ks, start)
                cvs.value = put(cvs.value, vs, start)
            else:
                ck.value = put(ck.value, k, start)
                cv.value = put(cv.value, v, start)
            # Scalar running index kept for callers that step every row
            # in lockstep (meaningless for mixed-depth slot batches —
            # positions are the authority either way).
            ci.value = start[0] + L
            from tensorflow_distributed_tpu.ops.flash_attention import (
                NEG_INF, window_keep)
            cols = jnp.arange(cfg.max_len)[None, None, :]
            # The SAME (pos - window, pos] band as training
            # (window_keep is the one construction), per row: cache
            # entries past each row's position — or older than the
            # window — are masked out. [1 | B, L, max_len].
            bias = jnp.where(
                window_keep(pos[:, :, None], cols, cfg.attn_window),
                0.0, float(NEG_INF))
            def grouped_attend(kc, vc, kscale=None, vscale=None):
                # ONE grouped attend for every cache layout (g == 1
                # covers MHA): narrow (GQA) caches stay narrow, and
                # int8 caches pass their per-(token, head) scales —
                # the scale-adjusted dots are mathematically exact
                # rescalings (q.dequant(K)^T = (q.K8^T) * kscale[col];
                # P.dequant(V) = (P * vscale[col]).V8), so no
                # dequantized cache is ever materialized and the only
                # full-cache HBM reads are int8. Rows are never fully
                # masked (the just-written diagonal entry at col
                # each row's position is always inside its band), so plain
                # softmax is safe.
                g = h // nk
                qg = q.reshape(B, L, nk, g, dh).astype(jnp.float32)
                s = jnp.einsum("bqngd,bknd->bngqk", qg,
                               kc.astype(jnp.float32))
                if kscale is not None:
                    s = s * kscale.transpose(0, 2, 1)[:, :, None, None]
                s = s / jnp.sqrt(jnp.asarray(dh, jnp.float32))
                s = s + bias[:, None, None]
                p = jax.nn.softmax(s, axis=-1)
                if vscale is not None:
                    p = p * vscale.transpose(0, 2, 1)[:, :, None, None]
                o = jnp.einsum("bngqk,bknd->bqngd", p,
                               vc.astype(jnp.float32))
                return o.reshape(B, L, h, dh).astype(q.dtype)

            if paged:
                # Gather the row's pages back into the SAME
                # [B, max_len, ...] logical layout the dense attend
                # reads — identical bytes in identical order, so the
                # shared attend below is numerically the dense one.
                def gathered(buf):
                    g = buf[page_table.astype(jnp.int32)]
                    return g.reshape((B, cfg.max_len) + buf.shape[2:])

                kc_v, vc_v = gathered(ck.value), gathered(cv.value)
                ks_v = gathered(cks.value) if quant else None
                vs_v = gathered(cvs.value) if quant else None
            else:
                kc_v, vc_v = ck.value, cv.value
                ks_v = cks.value if quant else None
                vs_v = cvs.value if quant else None
            if quant:
                out = grouped_attend(kc_v, vc_v, ks_v, vs_v)
            elif nk == h:
                out = full_attention(q, kc_v, vc_v, bias)
            else:
                out = grouped_attend(kc_v, vc_v)
        elif self.mesh is not None and self.mesh.shape[AXIS_SEQ] > 1:
            if cfg.attn_window:
                raise ValueError(
                    "attn_window with mesh.seq > 1 is not "
                    "implemented (the zigzag ring schedule is not "
                    "windowed); at W << L the window IS the "
                    "long-context strategy — use mesh.seq == 1")
            out = ring_attention(q, widen(k), widen(v), self.mesh,
                                 causal=cfg.causal)
        else:
            # Pallas flash kernel on TPU (shard_mapped over dp x tp when
            # the mesh is partitioned), XLA oracle elsewhere.
            out = attention(q, widen(k), widen(v), causal=cfg.causal,
                            window=cfg.attn_window, mesh=self.mesh,
                            allow_flash=cfg.use_flash)
        out = nn.DenseGeneral(
            features=cfg.d_model, axis=(-2, -1), use_bias=True,
            kernel_init=_maybe_partitioned(cfg, (AXIS_MODEL, None, None)),
            dtype=cfg.compute_dtype, name="out")(out)
        return out


def _norm(cfg, name: str):
    """Block normalization module per cfg.norm, f32 either way."""
    if cfg.norm == "rmsnorm":
        return nn.RMSNorm(dtype=jnp.float32, name=name)
    if cfg.norm == "layernorm":
        return nn.LayerNorm(dtype=jnp.float32, name=name)
    raise ValueError(f"norm {cfg.norm!r}; have ('layernorm', 'rmsnorm')")


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        def proj(name):
            return nn.Dense(
                cfg.d_ff,
                kernel_init=_maybe_partitioned(cfg, (None, AXIS_MODEL)),
                dtype=cfg.compute_dtype, name=name)

        if cfg.mlp_variant == "swiglu":
            x = nn.silu(proj("gate")(x)) * proj("up")(x)
        elif cfg.mlp_variant == "gelu":
            x = nn.gelu(proj("up")(x))
        else:
            raise ValueError(f"mlp_variant {cfg.mlp_variant!r}; "
                             f"have ('gelu', 'swiglu')")
        x = nn.Dense(cfg.d_model,
                     kernel_init=_maybe_partitioned(cfg, (AXIS_MODEL, None)),
                     dtype=cfg.compute_dtype, name="down")(x)
        return x


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    # NOTE: ``train`` is positional (not kw-only) so nn.remat can mark
    # it static by index — (self, x, train) -> static_argnums=(2,).
    @nn.compact
    def __call__(self, x: jax.Array, train: bool = False,
                 decode: bool = False,
                 positions: Optional[jax.Array] = None,
                 page_table: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        # Pre-LN (trains without warmup games, unlike BERT's post-LN).
        y = _norm(cfg, "ln1")(x)
        y = SelfAttention(cfg, self.mesh, name="attn")(
            y.astype(cfg.compute_dtype), train=train, decode=decode,
            positions=positions, page_table=page_table)
        y = nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)
        x = x + y
        y = _norm(cfg, "ln2")(x)
        if cfg.moe_experts > 0:
            if cfg.mlp_variant != "gelu":
                raise ValueError(
                    "mlp_variant has no effect with moe_experts > 0 "
                    "(MoeMlp replaces the block MLP)")
            from tensorflow_distributed_tpu.models.moe import MoeMlp
            y = MoeMlp(d_model=cfg.d_model, d_ff=cfg.d_ff,
                       num_experts=cfg.moe_experts, top_k=cfg.moe_top_k,
                       capacity_factor=cfg.moe_capacity_factor,
                       group_len=cfg.moe_group_len,
                       dispatch=cfg.moe_dispatch,
                       compute_dtype=cfg.compute_dtype,
                       expert_axis=cfg.moe_expert_axis,
                       partitioned=cfg.tp_partitioning,
                       name="moe_mlp")(y.astype(cfg.compute_dtype))
        else:
            y = Mlp(cfg, name="mlp")(y.astype(cfg.compute_dtype))
        y = nn.Dropout(cfg.dropout_rate, deterministic=not train)(y)
        out = x + y
        if cfg.health_taps:
            # f32 RMS of the block's residual-stream output, sown into
            # the transient "health" collection (a no-op unless the
            # caller made it mutable — train.step.apply_model does
            # during training). The per-layer activation-scale vital:
            # a block whose output RMS runs away precedes the loss
            # spike by many steps.
            self.sow("health", "act_rms", jnp.sqrt(jnp.mean(
                jnp.square(out.astype(jnp.float32)))))
        return out


class _LmHead(nn.Module):
    """The untied output projection, param-compatible with the nn.Dense
    it replaced (same 'kernel'/'bias' names, shapes, inits — checkpoints
    carry over), but able to hand out its parameters WITHOUT computing
    logits: the fused-CE path (ops/fused_ce.py) runs the head matmul
    inside the loss, chunk by chunk, so the model must expose the raw
    [D, V] kernel instead of a [B, L, V] product."""

    d_in: int
    d_out: int
    kernel_init: Any
    dtype: Any

    @nn.compact
    def __call__(self, x: Optional[jax.Array] = None):
        kernel = self.param("kernel", self.kernel_init,
                            (self.d_in, self.d_out))
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.d_out,))
        if x is None:
            return kernel, bias
        return (jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
                + bias.astype(self.dtype))


class TransformerLM(nn.Module):
    """Transformer LM backbone: tokens [B, L] int32 -> logits [B, L, V].

    ``extra_vocab`` widens the input embedding only (BERT's [MASK]
    sentinel); ``cfg.causal`` selects autoregressive attention (the GPT
    family) vs bidirectional (BERT)."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    extra_vocab: int = 0

    @nn.compact
    def __call__(self, tokens: jax.Array, *, train: bool = False,
                 decode: bool = False,
                 positions: Optional[jax.Array] = None,
                 page_table: Optional[jax.Array] = None,
                 features_only: bool = False):
        cfg = self.cfg
        if cfg.pos_emb not in ("learned", "rope"):
            raise ValueError(f"pos_emb {cfg.pos_emb!r}; "
                             f"have ('learned', 'rope')")
        B, L = tokens.shape
        emb_init = _dense_init()
        vocab_pad = 0
        if cfg.shard_vocab:
            if not cfg.tp_partitioning:
                raise ValueError(
                    "shard_vocab needs tp_partitioning (the pipelined "
                    "family manages its shell params without TP "
                    "metadata — use mesh.pipe for its memory)")
            emb_init = nn.with_partitioning(emb_init, (AXIS_MODEL, None))
            # Megatron-style vocab padding: round the table rows up to
            # a multiple of the TP axis so the shard is well-formed at
            # ANY real vocab (50257 is odd; BERT adds a sentinel row).
            # Padded rows are never looked up, and padded logits are
            # sliced off below before the loss sees them.
            tp = (dict(self.mesh.shape).get(AXIS_MODEL, 1)
                  if self.mesh is not None else 1)
            vocab_pad = (-(cfg.vocab_size + self.extra_vocab)) % tp
        emb = nn.Embed(cfg.vocab_size + self.extra_vocab + vocab_pad,
                       cfg.d_model,
                       embedding_init=emb_init, name="tok_emb")
        x = emb(tokens)
        if positions is None:
            if decode:
                # arange(L) would embed a continuation token at position
                # 0 while the cache attends it at the running index —
                # silently wrong logits. Make the caller say where.
                raise ValueError("decode=True requires positions")
            positions = jnp.arange(L)[None, :]
        if cfg.pos_emb == "learned":
            pos = nn.Embed(cfg.max_len, cfg.d_model,
                           embedding_init=_dense_init(), name="pos_emb")(
                positions)
            x = (x + pos).astype(cfg.compute_dtype)
        else:  # rope: no additive embedding; q/k rotate per layer
            x = x.astype(cfg.compute_dtype)
        if self.mesh is not None:
            # Pin activation layout: batch over "data", seq over "seq".
            x = jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(
                    self.mesh,
                    jax.sharding.PartitionSpec(AXIS_DATA, AXIS_SEQ, None)))

        block = Block
        if cfg.remat:
            # Rematerialize each block on backward: HBM for FLOPs, the
            # standard long-context trade. train/decode must be static
            # (indices 2,3 counting self) — they select branches.
            block = nn.remat(Block, static_argnums=(2, 3),
                             policy=resolve_remat_policy(cfg.remat_policy))
        for i in range(cfg.n_layers):
            x = block(cfg, self.mesh, name=f"layer_{i}")(x, train, decode,
                                                         positions,
                                                         page_table)
        x = _norm(cfg, "ln_f")(x)
        if features_only:
            # Hand the loss the pieces of the head instead of its
            # product: (features, head matrix, bias, vocab axis of the
            # matrix) — ops.fused_ce consumes them chunk by chunk
            # (single-rank scan, Pallas kernel, or at mesh.model > 1
            # the vocab-parallel form — padding rows are sliced off
            # here and re-derived where the TP dispatch needs them).
            xc = x.astype(cfg.compute_dtype)
            if cfg.tie_embeddings:
                return xc, emb.embedding[:cfg.vocab_size], None, 0
            head_pad = ((-cfg.vocab_size) % tp if cfg.shard_vocab else 0)
            head = _LmHead(cfg.d_model, cfg.vocab_size + head_pad,
                           _maybe_partitioned(cfg, (None, AXIS_MODEL)),
                           cfg.compute_dtype, name="lm_head")
            kernel, bias = head(None)
            if head_pad:
                kernel, bias = (kernel[:, :cfg.vocab_size],
                                bias[:cfg.vocab_size])
            return xc, kernel, bias, 1
        if cfg.tie_embeddings:
            # Cast the shared table to compute dtype so the logits
            # matmul (the model's largest) stays on the bf16 MXU path
            # like the untied head. With shard_vocab the table rows are
            # split over "model", so the einsum emits vocab-sharded
            # logits (same layout as the untied sharded head); without
            # it the tied logits compute replicated.
            table = emb.embedding.astype(cfg.compute_dtype)
            logits = jnp.einsum("...d,vd->...v",
                                x.astype(cfg.compute_dtype), table)
            logits = logits[..., :cfg.vocab_size]  # drop sentinel rows
        else:
            # Same padding treatment for the untied head's output dim
            # (the kernel's vocab dim is TP-sharded whenever
            # tp_partitioning is on).
            head_pad = ((-cfg.vocab_size) % tp if cfg.shard_vocab else 0)
            logits = _LmHead(
                cfg.d_model, cfg.vocab_size + head_pad,
                _maybe_partitioned(cfg, (None, AXIS_MODEL)),
                cfg.compute_dtype, name="lm_head")(
                x.astype(cfg.compute_dtype))
            if head_pad:
                logits = logits[..., :cfg.vocab_size]
        return logits.astype(jnp.float32)


class BertMLM(TransformerLM):
    """Encoder-only masked-LM (bidirectional, +[MASK] sentinel)."""

    extra_vocab: int = 1


class CausalLM(TransformerLM):
    """Decoder-only autoregressive LM (the GPT family). Construct with
    a ``causal=True`` config (the factories below enforce it)."""


def bert_base_mlm(mesh: Optional[Mesh] = None, size: str = "base",
                  **overrides) -> BertMLM:
    """Factory for the registry. ``size``: "base" (BERT-base) or "tiny"
    (test scale); ``overrides`` are TransformerConfig fields."""
    _auto_expert_axis(mesh, overrides)
    _auto_tp_partitioning(mesh, overrides)
    if size == "base":
        cfg = bert_base_config(**overrides)
    elif size == "tiny":
        cfg = tiny_config(**overrides)
    else:
        raise ValueError(f"bert_mlm size {size!r}; have ('base', 'tiny')")
    return BertMLM(cfg, mesh)


def bert_tiny_mlm(mesh: Optional[Mesh] = None, **overrides) -> BertMLM:
    return BertMLM(tiny_config(**overrides), mesh)


def gpt2_small_config(**overrides) -> TransformerConfig:
    """GPT-2-small (12L x 768d x 12H, learned positions, pre-LN) — the
    flagship config, shared by gpt_lm and the pipelined factory so the
    two families can never drift apart."""
    return dataclasses.replace(
        TransformerConfig(vocab_size=50257, d_model=768, n_layers=12,
                          n_heads=12, d_ff=3072, max_len=1024,
                          causal=True),
        **overrides)


# The GPT-2 ladder (Radford et al. 2019 table 2): d_ff = 4 * d_model
# throughout; head dim stays 64. "small" remains the measured flagship
# (LMBENCH artifacts); the larger rungs are what --remat,
# --param-partition fsdp/zero1, --ce-chunk and the pipeline exist for.
GPT2_SIZES = {
    "small": dict(d_model=768, n_layers=12, n_heads=12, d_ff=3072),
    "medium": dict(d_model=1024, n_layers=24, n_heads=16, d_ff=4096),
    "large": dict(d_model=1280, n_layers=36, n_heads=20, d_ff=5120),
    "xl": dict(d_model=1600, n_layers=48, n_heads=25, d_ff=6400),
}


def gpt_lm(mesh: Optional[Mesh] = None, size: str = "small",
           **overrides) -> CausalLM:
    """GPT-style decoder-only LM. ``size``: the GPT-2 ladder
    ("small" 124M-class / "medium" 355M / "large" 774M / "xl" 1.6B
    backbone shapes, GPT2_SIZES) or "tiny" (test scale). No reference
    counterpart (the reference has no sequence models, SURVEY.md §5)
    — designed TPU-first like the rest of this family."""
    overrides["causal"] = True
    _auto_expert_axis(mesh, overrides)
    _auto_tp_partitioning(mesh, overrides)
    if size in GPT2_SIZES:
        cfg = gpt2_small_config(**{**GPT2_SIZES[size], **overrides})
    elif size == "tiny":
        cfg = tiny_config(**overrides)
    else:
        raise ValueError(f"gpt_lm size {size!r}; have "
                         f"({', '.join(GPT2_SIZES)}, tiny)")
    return CausalLM(cfg, mesh)


# The factory-default expert count moe_lm applies when none is given.
# Named so the auto-layout planner's model facts (analysis/planner/
# candidates.model_facts) prune expert-axis shapes against the SAME
# number the scorer's real build uses.
MOE_DEFAULT_EXPERTS = 4


def moe_lm(mesh: Optional[Mesh] = None, size: str = "tiny",
           **overrides) -> CausalLM:
    """Expert-parallel causal LM ("moe_lm" registry entry): the GPT
    family with every MLP a top-2 MoE (models/moe.py). No reference
    counterpart (SURVEY.md §2b "Expert parallel: NO")."""
    overrides.setdefault("moe_experts", MOE_DEFAULT_EXPERTS)
    if overrides["moe_experts"] <= 0:
        raise ValueError("moe_lm needs moe_experts > 0")
    return gpt_lm(mesh=mesh, size=size, **overrides)  # auto expert axis
