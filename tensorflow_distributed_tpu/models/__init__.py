"""Model zoo.

``mnist_cnn`` is the reference-parity model (the CNN duplicated across
mnist_python_m.py:93-128, mnist_single.py:55-88 and the notebook — here
it exists exactly once). ResNet and the transformer families extend the
same train-step machinery to the BASELINE.json scale-out configs.
"""

from typing import Optional

import jax.numpy as jnp

from tensorflow_distributed_tpu.models.cnn import MnistCNN  # noqa: F401

MODEL_NAMES = ("mnist_cnn", "resnet20", "resnet50", "bert_mlm", "gpt_lm",
               "pipelined_lm", "moe_lm")

# Families whose train state carries mutable variable collections
# (BatchNorm statistics) — maintained HERE, next to the registry, so
# capability checks (e.g. local SGD's no-divergent-stats rule,
# config.validate) track new models; train.local_sgd.stack_state's
# runtime extra-state check is the backstop.
MUTABLE_EXTRA_MODELS = ("resnet20", "resnet50")


def build_model(name: str, mesh=None, dropout_rate: Optional[float] = None,
                init_scheme: str = "improved",
                compute_dtype=jnp.bfloat16, **overrides):
    """Explicit per-family dispatch (no kwargs guessing): each family
    takes what it understands.

    ``init_scheme`` is the CNN's reference-vs-improved switch
    (mnist_python_m.py:185-196); the other families have no reference
    counterpart to be faithful to and ignore it. ``mesh`` matters only
    to the transformer (ring attention needs it); ``overrides`` are
    TransformerConfig fields.
    """
    from tensorflow_distributed_tpu.models import cnn, resnet, transformer

    if name not in ("bert_mlm", "gpt_lm", "pipelined_lm", "moe_lm"):
        overrides.pop("size", None)  # presets are transformer-family only
    if name == "mnist_cnn":
        kw = dict(init_scheme=init_scheme, compute_dtype=compute_dtype)
        if dropout_rate is not None:
            kw["dropout_rate"] = dropout_rate
        return cnn.MnistCNN(**kw)
    if name == "resnet20":
        return resnet.resnet20(compute_dtype=compute_dtype, **overrides)
    if name == "resnet50":
        return resnet.resnet50(compute_dtype=compute_dtype, **overrides)
    if name == "bert_mlm":
        if dropout_rate is not None:
            overrides.setdefault("dropout_rate", dropout_rate)
        overrides.setdefault("compute_dtype", compute_dtype)
        return transformer.bert_base_mlm(mesh=mesh, **overrides)
    if name == "gpt_lm":
        if dropout_rate is not None:
            overrides.setdefault("dropout_rate", dropout_rate)
        overrides.setdefault("compute_dtype", compute_dtype)
        return transformer.gpt_lm(mesh=mesh, **overrides)
    if name == "moe_lm":
        if dropout_rate is not None:
            overrides.setdefault("dropout_rate", dropout_rate)
        overrides.setdefault("compute_dtype", compute_dtype)
        return transformer.moe_lm(mesh=mesh, **overrides)
    if name == "pipelined_lm":
        from tensorflow_distributed_tpu.models import pipelined
        if dropout_rate is not None:
            overrides.setdefault("dropout_rate", dropout_rate)
        overrides.setdefault("compute_dtype", compute_dtype)
        if mesh is None:
            raise ValueError("pipelined_lm needs a mesh (pipe axis)")
        return pipelined.pipelined_lm(mesh=mesh, **overrides)
    raise ValueError(f"unknown model {name!r}; have {sorted(MODEL_NAMES)}")
