"""Model zoo.

``mnist_cnn`` is the reference-parity model (the CNN duplicated across
mnist_python_m.py:93-128, mnist_single.py:55-88 and the notebook — here
it exists exactly once). ResNet and the transformer families extend the
same train-step machinery to the BASELINE.json scale-out configs.
"""

from tensorflow_distributed_tpu.models.cnn import MnistCNN  # noqa: F401


def build_model(name: str, **kw):
    from tensorflow_distributed_tpu.models import cnn, resnet, transformer
    registry = {
        "mnist_cnn": cnn.MnistCNN,
        "resnet20": resnet.resnet20,
        "resnet50": resnet.resnet50,
        "bert_mlm": transformer.bert_base_mlm,
    }
    if name not in registry:
        raise ValueError(f"unknown model {name!r}; have {sorted(registry)}")
    return registry[name](**kw)
