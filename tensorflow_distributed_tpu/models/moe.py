"""Mixture-of-Experts MLP with expert parallelism (GShard-style).

The reference has no expert/routing code (SURVEY.md §2b checklist:
"Expert parallel: NO") — beyond-reference capability, built the
TPU-native way: expert weights carry a leading expert dim partitioned
over a mesh axis, so XLA's SPMD partitioner derives the token
all_to_alls from sharding propagation — nobody writes a collective by
hand. Token movement has two interchangeable formulations sharing one
routing computation (``dispatch`` knob):
- "dense" (default): one-hot dispatch/combine einsums (the
  Mesh-TensorFlow/GShard formulation) — pure batched einsums on the
  MXU, no gather/scatter HLOs, the layout EP sharding is proven on.
- "scatter": the same assignments as a slot scatter-add into the
  expert buffers and a gather back — emits real scatter/gather HLOs,
  moves O(K) rows per token instead of spending O(E*C) einsum FLOPs
  per token, and never materializes the [S, E, C] one-hot tensors.
Identical masks, positions, capacity drops, gates, and aux sows either
way (gradient-level parity pinned in tests/test_moe.py, including an
EP-sharded train-step A/B).

Mechanics (top-2, capacity-factor c):
- gate logits [G, S, E] in f32; top-1 and top-2 assignments become
  one-hot masks; per-expert positions come from cumsums; tokens beyond
  the expert's capacity C = ceil(c * k * S / E) are dropped (their
  combine weight is 0, so they pass through the residual unchanged).
- dispatch [G, S, E, C] (0/1) routes tokens to expert buffers
  [G, E, C, M]; experts apply their own MLP weights [E, M, H]/[E, H, M];
  combine (dispatch * gate prob) returns them to [G, S, M].

Observability / losses, sown into the "moe_aux" collection (the MoE
loss collects them with ``collect_aux`` and weights the first two into
the objective; see train.tasks.make_moe_loss):
- "load_balance": E * sum_e f_e * p_e over ALL top-k assignments
  (f_e = routed fraction / K, so sum_e f_e == 1 and a uniform router
  scores exactly 1.0) — the Switch loss when K == 1, the
  DeepSeek/Mixtral-style generalization when K > 1.
- "z_loss": mean (logsumexp of router logits)^2 — the ST-MoE router
  z-loss that keeps gate logits from drifting to magnitudes where
  softmax saturates (weight 0 by default; a TrainConfig knob).
- "dropped_fraction": fraction of (token, k) routing slots past
  expert capacity — drops are silent passthroughs in the math, so
  this is the ONLY place overflow is visible. Reported as a train
  metric, never part of the objective.

Expert axis: "model" by default — expert parallelism composes with the
existing mesh without a fifth axis; a dedicated "expert" mesh axis
(MeshConfig.expert) is supported via the ``expert_axis`` knob.

Scale envelope (measured: MOEBENCH.json, benchmarks/moebench.py).
The dense [G, S, E, C] dispatch/combine tensors are O(S * E * C) f32
each with C = ceil(c*K*S/E), i.e. O(c*K*S^2) PER GROUP at any E —
quadratic in sequence length at fixed capacity factor:

    seq  1024:   20 MiB/group   (measured on chip: 65k tok/s, 37.6%
    seq  4096:  320 MiB/group    active-param MFU, E=8 d768 L12;
    seq  8192: 1.25 GiB/group    dispatch einsums are ~25% of step
    seq 32768:   20 GiB/group    FLOPs at seq 1024 — C grows with S,
                                 so this share grows too)

Inside the envelope (seq <= ~4k per group on a 16G chip, any E) the
formulation is the right TPU trade: pure batched einsums on the MXU,
zero gather/scatter, and GSPMD-derived all_to_alls. Past it, set
``group_len`` (``--moe-group-len``): each row's sequence splits into
independent routing groups of that length, so capacity — and with it
BOTH the dispatch tensors AND the dispatch-einsum FLOPs (each is
O(C) per token) — scales with the GROUP length, not the full
sequence: seq 32768 at group_len 1024 costs 32 x 20 MiB instead of
one 20 GiB tensor, and the measured seq-4096 win (1.28x tokens/s,
MOEBENCH/PARITY) is mostly those saved einsum FLOPs. Or combine with sequence parallelism so each seq shard routes
its own slice. A sorted/ragged (megablocks-style) dispatch would need
a Pallas grouped-matmul kernel with scalar-prefetch block indexing to
beat this on TPU; not implemented — the group-length knob covers the
practical range first.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from tensorflow_distributed_tpu.parallel.mesh import AXIS_MODEL

# Every MoeMlp sows exactly these names (in this order) per apply.
AUX_NAMES = ("load_balance", "z_loss", "dropped_fraction")


def collect_aux(col) -> dict:
    """Mean per sow-name over every MoE layer in a "moe_aux" collection.

    ``col`` is the (possibly nested) dict flax returns for the mutable
    "moe_aux" collection: {layer_path...: {name: (value, ...)}}. Returns
    {name: scalar} with each layer's sown values averaged — the shape
    the MoE objective and train metrics consume (train.tasks).
    """
    acc: dict = {}

    def walk(node):
        for k, v in node.items():
            if isinstance(v, dict):
                walk(v)
            else:  # a tuple of sown values (one per sow call)
                vals = list(v) if isinstance(v, (tuple, list)) else [v]
                acc.setdefault(k, []).extend(vals)

    walk(col)
    return {k: sum(v) / len(v) for k, v in acc.items()}


class MoeMlp(nn.Module):
    """Drop-in replacement for the dense MLP inside a Block."""

    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    compute_dtype: Any = jnp.bfloat16
    expert_axis: str = AXIS_MODEL
    partitioned: bool = True  # False inside manual shard_maps (pipeline)
    # Routing-group length: 0 = the whole sequence is one group (GShard
    # default). Setting S' < S splits each row's sequence into S/S'
    # contiguous groups routed independently — capacity AND the
    # [.., S', E, C'] dispatch tensors scale with S' (C' = c*K*S'/E),
    # which is the in-formulation answer to the O(S^2) envelope above.
    # Load-balance pressure becomes per-chunk (stricter, same optimum).
    group_len: int = 0
    # Token movement formulation. "dense" (GShard): one-hot [S, E, C]
    # dispatch/combine einsums — pure MXU, but O(E*C) FLOPs per token
    # (~25% of a measured E=8 step, MOEBENCH.json) and O(S*E*C)
    # memory. "scatter": the SAME routing (identical masks, positions,
    # capacity drops, aux losses) expressed as a scatter-add into the
    # [E, C, M] expert buffers and a gather back — O(K) moved rows per
    # token, no one-hot tensors at all. Expert matmuls are identical
    # einsums either way. Dense stays the default: its E-dim einsum
    # operands are what GSPMD's expert-axis all_to_all derivation is
    # proven on; scatter is the measured-faster single-replica path.
    dispatch: str = "dense"  # dense | scatter

    def _winit(self, names):
        init = nn.initializers.normal(stddev=0.02)
        return nn.with_partitioning(init, names) if self.partitioned else init

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        G0, S0, M0 = x.shape
        # Sequences at or below group_len route as one group — decode
        # (S0 == 1) and short prefills must not crash on a knob meant
        # for long training sequences.
        if self.group_len and S0 > self.group_len:
            if S0 % self.group_len:
                raise ValueError(
                    f"seq {S0} not divisible by group_len "
                    f"{self.group_len}")
            x = x.reshape(G0 * (S0 // self.group_len), self.group_len,
                          M0)
        G, S, M = x.shape
        E, K = self.num_experts, self.top_k
        if K > E:
            # The routing loop would argmax an exhausted mask and pick
            # expert 0 with full gate weight on the extra iterations —
            # silent degradation; refuse instead (config.validate
            # catches the CLI path; this guards direct construction
            # and family-default expert counts).
            raise ValueError(f"top_k {K} > num_experts {E}")
        if self.dispatch not in ("dense", "scatter"):
            raise ValueError(f"dispatch {self.dispatch!r}; "
                             "have ('dense', 'scatter')")
        C = max(1, math.ceil(self.capacity_factor * K * S / E))

        gate_w = self.param("gate", self._winit((None, None)), (M, E),
                            jnp.float32)
        logits = x.astype(jnp.float32) @ gate_w            # [G, S, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k one-hot masks + gates, built iteratively (K is 1 or 2).
        masks, gates = [], []
        remaining = probs
        for _ in range(K):
            idx = jnp.argmax(remaining, axis=-1)           # [G, S]
            mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
            gates.append(jnp.sum(probs * mask, axis=-1))   # [G, S]
            masks.append(mask)
            remaining = remaining * (1.0 - mask)

        # Positions within each expert's buffer: cumulative count of
        # prior assignments (top-1 first, then top-2 after all top-1).
        pos, used = [], jnp.zeros((G, 1, E), jnp.float32)
        for mask in masks:
            cum = jnp.cumsum(mask, axis=1) - mask + used   # [G, S, E]
            pos.append(jnp.sum(cum * mask, axis=-1))       # [G, S]
            used = used + jnp.sum(mask, axis=1, keepdims=True)

        # Load-balancing aux loss over ALL top-k assignments: f_e is the
        # routed fraction across every (token, k) slot divided by K, so
        # sum_e f_e == 1 and a perfectly uniform router scores exactly
        # 1.0 for any K. Reduces to Switch Transformer eq. 4-6 at K=1;
        # the K>1 form is the DeepSeek/Mixtral-style generalization.
        f = jnp.mean(sum(masks), axis=(0, 1)) / K          # [E]
        p = jnp.mean(probs, axis=(0, 1))                   # [E]
        self.sow("moe_aux", "load_balance", E * jnp.sum(f * p))
        # ST-MoE router z-loss: mean squared logsumexp of the gate
        # logits — bounds logit magnitudes so the routing softmax stays
        # in a trainable regime. Objective weight is a config knob
        # (train.tasks.make_moe_loss); 0 disables it.
        z = jax.nn.logsumexp(logits, axis=-1)              # [G, S]
        self.sow("moe_aux", "z_loss", jnp.mean(jnp.square(z)))

        wi = self.param("wi", self._winit((self.expert_axis, None, None)),
                        (E, M, self.d_ff), jnp.float32)
        wo = self.param("wo", self._winit((self.expert_axis, None, None)),
                        (E, self.d_ff, M), jnp.float32)
        dt = self.compute_dtype

        # Per-(token, k) keep flag, normalized gate, and expert-buffer
        # slot, shared by both formulations so routing/drop semantics
        # are identical by construction.
        denom = sum(gates) if K > 1 else None
        gks = [g / jnp.maximum(denom, 1e-9) if denom is not None else g
               for g in gates]
        withins = [(ps < C).astype(jnp.float32) * jnp.sum(mask, -1)
                   for mask, ps in zip(masks, pos)]
        kept = sum(jnp.sum(w) for w in withins) / (G * S * K)
        # Overflowed routing slots are silent zeros in the math (the
        # token passes through the residual unchanged) — surface them.
        self.sow("moe_aux", "dropped_fraction",
                 jax.lax.stop_gradient(1.0 - kept))

        if self.dispatch == "scatter":
            # Slot d = e*C + pos for kept (token, k) pairs; dropped
            # pairs target the dump row E*C. One scatter-add fills the
            # expert buffers (slots are unique by construction: pos is
            # a per-expert running count), one gather + gate-weighted
            # sum brings expert outputs home. AD gives the transposes
            # (gather <-> scatter) for free.
            gidx = jnp.arange(G)[:, None]                  # [G, 1]
            buf = jnp.zeros((G, E * C + 1, M), dt)
            ds_ = []
            for mask, ps, within in zip(masks, pos, withins):
                e_id = jnp.argmax(mask, axis=-1)           # [G, S]
                d = jnp.where(within > 0,
                              e_id * C + ps.astype(jnp.int32), E * C)
                buf = buf.at[gidx, d].add(
                    x.astype(dt) * within[..., None].astype(dt))
                ds_.append(d)
            xin = buf[:, :E * C].reshape(G, E, C, M)       # [G, E, C, M]
            h = jax.nn.gelu(
                jnp.einsum("gecm,emf->gecf", xin, wi.astype(dt)))
            out = jnp.einsum("gecf,efm->gecm", h, wo.astype(dt))
            out_pad = jnp.concatenate(
                [out.reshape(G, E * C, M), jnp.zeros((G, 1, M), dt)], 1)
            y = sum(out_pad[gidx, d] * gk[..., None].astype(dt)
                    for d, gk in zip(ds_, gks))
            return y.astype(x.dtype).reshape(G0, S0, M0)

        # dispatch/combine [G, S, E, C]; tokens past capacity drop out.
        dispatch = jnp.zeros((G, S, E, C), jnp.float32)
        combine = jnp.zeros((G, S, E, C), jnp.float32)
        for mask, gk, ps, within in zip(masks, gks, pos, withins):
            loc = jax.nn.one_hot(ps.astype(jnp.int32), C,
                                 dtype=jnp.float32)        # [G, S, C]
            sel = mask[..., None] * loc[..., None, :]      # [G, S, E, C]
            sel = sel * within[..., None, None]
            dispatch = dispatch + sel
            combine = combine + sel * gk[..., None, None]

        # Token shuffle in, expert MLPs, shuffle out — the einsums whose
        # E-dim sharding makes GSPMD emit the all_to_alls.
        xin = jnp.einsum("gsec,gsm->egcm", dispatch.astype(dt),
                         x.astype(dt))                     # [E, G, C, M]
        h = jax.nn.gelu(jnp.einsum("egcm,emf->egcf", xin, wi.astype(dt)))
        out = jnp.einsum("egcf,efm->egcm", h, wo.astype(dt))
        y = jnp.einsum("gsec,egcm->gsm", combine.astype(dt), out)
        return y.astype(x.dtype).reshape(G0, S0, M0)
