"""Pipeline-parallel transformer LM.

The transformer block stack as S pipeline stages over the "pipe" mesh
axis (parallel.pipeline). Embeddings and the LM head run replicated
outside the pipeline (they're cheap); the block stack — where the
FLOPs are — runs stage-sharded with the GPipe microbatch schedule.

Unlike models/transformer.py (an nn.Module whose GSPMD sharding comes
from param metadata), the pipelined variant owns its params as ONE
stacked pytree (block leaves [n_layers, ...] regrouped to
[S, layers_per_stage, ...] and pipe-sharded via nn.Partitioned boxes),
because the pipeline schedule needs to slice stages explicitly inside
shard_map. It duck-types the flax surface create_train_state/apply_model
consume: ``init(key, tokens, train=False) -> {"params": ...}`` and
``apply(variables, tokens, *, train=..., rngs=...)``.

v1 scope: composes with the "data" axis (activations stay
batch-sharded under GSPMD); "model"/"seq" must be 1 (TP/SP inside a
pipe-restricted shard_map is a follow-up); dropout is disabled (rng
plumbing through the scanned schedule isn't wired).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflow_distributed_tpu.models.transformer import (
    Block, TransformerConfig, _dense_init, resolve_remat_policy,
    tiny_config)
from tensorflow_distributed_tpu.parallel.mesh import (
    AXIS_MODEL, AXIS_PIPE, AXIS_SEQ)
from tensorflow_distributed_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)


class _Shell(nn.Module):
    """Embeddings + final LN + LM head — everything outside the pipe."""

    cfg: TransformerConfig
    extra_vocab: int = 0

    def setup(self):
        cfg = self.cfg
        self.tok_emb = nn.Embed(cfg.vocab_size + self.extra_vocab,
                                cfg.d_model, embedding_init=_dense_init(),
                                name="tok_emb")
        self.pos_emb = nn.Embed(cfg.max_len, cfg.d_model,
                                embedding_init=_dense_init(),
                                name="pos_emb")
        self.ln_f = nn.LayerNorm(dtype=jnp.float32, name="ln_f")
        self.lm_head = nn.Dense(cfg.vocab_size,
                                kernel_init=_dense_init(),
                                dtype=cfg.compute_dtype, name="lm_head")

    def embed(self, tokens: jax.Array) -> jax.Array:
        L = tokens.shape[1]
        x = self.tok_emb(tokens) + self.pos_emb(jnp.arange(L)[None, :])
        return x.astype(self.cfg.compute_dtype)

    def head(self, x: jax.Array) -> jax.Array:
        x = self.ln_f(x).astype(self.cfg.compute_dtype)
        return self.lm_head(x).astype(jnp.float32)

    def __call__(self, tokens: jax.Array) -> jax.Array:  # init path only
        return self.head(self.embed(tokens))


class PipelinedLM:
    """Decoder/encoder LM with the block stack pipeline-parallel."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh,
                 num_microbatches: int = 4, extra_vocab: int = 0):
        if cfg.dropout_rate:
            raise ValueError("pipelined variant: dropout_rate must be 0")
        if cfg.tp_partitioning:
            raise ValueError(
                "pipelined variant needs tp_partitioning=False (flax "
                "DenseGeneral re-applies the TP constraint inside the "
                "pipe shard_map; see TransformerConfig.tp_partitioning)")
        if cfg.use_flash:
            raise ValueError(
                "pipelined variant needs use_flash=False (Mosaic calls "
                "can't sit inside the partial-manual pipe shard_map; "
                "see TransformerConfig.use_flash)")
        if mesh.shape[AXIS_MODEL] != 1 or mesh.shape[AXIS_SEQ] != 1:
            raise ValueError("pipelined variant composes with 'data' "
                             "only; set mesh model=seq=1")
        S = mesh.shape[AXIS_PIPE]
        if cfg.n_layers % S:
            raise ValueError(
                f"{cfg.n_layers} layers not divisible by {S} stages")
        self.cfg = cfg
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self._shell = _Shell(cfg, extra_vocab)
        # Blocks see no mesh: inside the pipe-restricted shard_map the
        # attention dispatcher must not try its own dp/tp shard_map.
        self._block = Block(cfg, None)

    # -- flax-compatible surface -----------------------------------------

    def init(self, key: jax.Array, tokens: jax.Array,
             train: bool = False) -> Any:
        del train
        cfg = self.cfg
        k_shell, k_blocks = jax.random.split(key)
        shell_params = self._shell.init(k_shell, tokens)["params"]
        x = jnp.zeros((tokens.shape[0], tokens.shape[1], cfg.d_model),
                      cfg.compute_dtype)
        layer_keys = jax.random.split(k_blocks, cfg.n_layers)
        # Unbox inside the vmap: Block's TP partition metadata (rank-N
        # names) would be stale on the rank-N+2 stacked leaves — the
        # pipelined variant enforces model=seq=1, so dropping it is
        # sound; pipe-axis boxes are added below with full-rank names.
        stacked = jax.vmap(lambda k: nn.meta.unbox(
            self._block.init(k, x, False)["params"]))(layer_keys)
        staged = stack_stage_params(stacked,
                                    self.mesh.shape[AXIS_PIPE])
        boxed = jax.tree_util.tree_map(
            lambda p: nn.Partitioned(
                p, names=(AXIS_PIPE,) + (None,) * (p.ndim - 1)), staged)
        return {"params": {"shell": shell_params, "blocks": boxed}}

    def apply(self, variables: Any, tokens: jax.Array, *,
              train: bool = False, rngs: Optional[Any] = None) -> jax.Array:
        del rngs  # dropout disabled (checked in __init__)
        p = variables["params"]
        x = self._shell.apply({"params": p["shell"]}, tokens,
                              method="embed")

        def stage_fn(stage_params, x_mb):
            # stage_params leaves: [layers_per_stage, ...]; run the
            # stage's blocks in order via scan-over-layers.
            def one_layer(x, layer_p):
                return self._block.apply({"params": layer_p}, x, False), None
            if self.cfg.remat:
                # --remat for the pipelined family: rematerialize each
                # block on backward (cfg.remat_policy as in
                # models/transformer.py), so activation memory per stage
                # is O(1) blocks instead of O(layers_per_stage).
                one_layer = jax.checkpoint(
                    one_layer,
                    policy=resolve_remat_policy(self.cfg.remat_policy))
            y, _ = jax.lax.scan(one_layer, x_mb, stage_params)
            return y

        x = pipeline_apply(stage_fn, p["blocks"], x, self.mesh,
                           self.num_microbatches)
        return self._shell.apply({"params": p["shell"]}, x, method="head")


def pipelined_lm(mesh: Mesh, size: str = "tiny", causal: bool = True,
                 num_microbatches: int = 4, **overrides) -> PipelinedLM:
    """Registry factory ("pipelined_lm"). Sizes: "tiny" (tests/CI)."""
    overrides.setdefault("dropout_rate", 0.0)
    overrides.setdefault("n_layers", 4)  # tiny default (2) < common S
    overrides["causal"] = causal
    overrides["tp_partitioning"] = False  # see TransformerConfig notes
    overrides["use_flash"] = False
    if size != "tiny":
        raise ValueError(f"pipelined_lm size {size!r}; have ('tiny',)")
    return PipelinedLM(tiny_config(**overrides), mesh, num_microbatches)
