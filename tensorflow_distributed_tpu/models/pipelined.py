"""Pipeline-parallel transformer LM.

The transformer block stack as S pipeline stages over the "pipe" mesh
axis (parallel.pipeline). Embeddings and the LM head run replicated
outside the pipeline (they're cheap); the block stack — where the
FLOPs are — runs stage-sharded with the GPipe microbatch schedule.

Unlike models/transformer.py (an nn.Module whose GSPMD sharding comes
from param metadata), the pipelined variant owns its params as ONE
stacked pytree (block leaves [n_layers, ...] regrouped to
[S, layers_per_stage, ...] and pipe-sharded via nn.Partitioned boxes),
because the pipeline schedule needs to slice stages explicitly inside
shard_map. It duck-types the flax surface create_train_state/apply_model
consume: ``init(key, tokens, train=False) -> {"params": ...}`` and
``apply(variables, tokens, *, train=..., rngs=...)``.

Composition: the pipe shard_map manualizes ONLY the "pipe" axis, so
"data" (batch) and "model" (TP) sharding of activations and stage
params continue to be handled by the surrounding GSPMD partitioner.
TP metadata can't ride flax module boxes here (tp_partitioning=False,
see TransformerConfig) — instead init() re-attaches Megatron-style
"model" names to the STACKED leaves by key-path suffix (_TP_SUFFIX
rules matching models/transformer.py's layout conventions), so
PP x TP x DP runs from one boxed pytree. "seq" > 1 composes too
(causal only): the Block routes seq-sharded activations to ring
attention, whose shard_map nests over the remaining auto axes inside
the pipe-manual region exactly like the flash dispatcher's
(parallel.ring_attention; pinned by
tests/test_pipelined_modern.py::test_pipelined_ring_attention_parity).
Dropout is plumbed: pipeline_apply folds the step key over
(microbatch, stage), stages fold per-layer.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tensorflow_distributed_tpu.models.transformer import (
    Block, TransformerConfig, _dense_init, _LmHead, _norm,
    resolve_remat_policy, tiny_config)
from tensorflow_distributed_tpu.parallel.mesh import (
    AXIS_MODEL, AXIS_PIPE, AXIS_SEQ)
from tensorflow_distributed_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)
from tensorflow_distributed_tpu.parallel.sharding import path_key

# Megatron-style TP ("model" axis) names for stacked block leaves, by
# key-path suffix — the same layout conventions models/transformer.py
# attaches via nn.with_partitioning (its module docstring table). Tuples
# are the names for the leaf's ORIGINAL dims; init() prepends
# (pipe, None) for the [S, layers_per_stage, ...] stacking dims.
_TP_SUFFIX = [
    (("attn", "qkv", "kernel"), (None, None, AXIS_MODEL, None)),
    (("attn", "qkv", "bias"), (None, AXIS_MODEL, None)),
    # GQA splits qkv into separate q and kv projections
    # (models/transformer.py SelfAttention): q shards its head dim like
    # qkv; the NARROW kv kernels stay replicated by design there too
    # (n_kv_heads is typically smaller than the TP axis) — so no kv
    # entry here, matching the non-pipelined layout exactly.
    (("attn", "q", "kernel"), (None, AXIS_MODEL, None)),
    (("attn", "q", "bias"), (AXIS_MODEL, None)),
    (("attn", "out", "kernel"), (AXIS_MODEL, None, None)),
    (("mlp", "up", "kernel"), (None, AXIS_MODEL)),
    (("mlp", "up", "bias"), (AXIS_MODEL,)),
    (("mlp", "gate", "kernel"), (None, AXIS_MODEL)),  # swiglu
    (("mlp", "gate", "bias"), (AXIS_MODEL,)),
    (("mlp", "down", "kernel"), (AXIS_MODEL, None)),
    # MoE expert weights: expert-parallel over the same axis
    # (models/moe.py's default expert_axis).
    (("moe_mlp", "wi"), (AXIS_MODEL, None, None)),
    (("moe_mlp", "wo"), (AXIS_MODEL, None, None)),
]


def _tp_names(path, ndim, lead=2):
    """TP axis names for a stacked leaf's ORIGINAL dims; ``lead`` is
    how many stacking dims were prepended ([S, lps] plain, [S, V, lps]
    interleaved)."""
    keys = path_key(path)
    for suffix, names in _TP_SUFFIX:
        if keys[-len(suffix):] == suffix:
            assert len(names) == ndim - lead, (keys, names, ndim)
            return names
    return (None,) * (ndim - lead)


class _Shell(nn.Module):
    """Embeddings + final LN + LM head — everything outside the pipe."""

    cfg: TransformerConfig
    extra_vocab: int = 0

    def setup(self):
        cfg = self.cfg
        self.tok_emb = nn.Embed(cfg.vocab_size + self.extra_vocab,
                                cfg.d_model, embedding_init=_dense_init(),
                                name="tok_emb")
        if cfg.pos_emb == "learned":
            # rope has no additive table — q/k rotate inside each block.
            self.pos_emb = nn.Embed(cfg.max_len, cfg.d_model,
                                    embedding_init=_dense_init(),
                                    name="pos_emb")
        self.ln_f = _norm(cfg, "ln_f")
        if not cfg.tie_embeddings:
            # Tied: the head IS tok_emb (both live in this one shell
            # module, so tying is shell-local — same scheme as
            # models/transformer.py's TransformerLM). _LmHead is the
            # Dense-compatible head that can hand out its kernel/bias
            # without computing logits (the fused-CE path).
            self.lm_head = _LmHead(cfg.d_model, cfg.vocab_size,
                                   _dense_init(),
                                   cfg.compute_dtype,
                                   name="lm_head")

    def embed(self, tokens: jax.Array) -> jax.Array:
        L = tokens.shape[1]
        x = self.tok_emb(tokens)
        if self.cfg.pos_emb == "learned":
            x = x + self.pos_emb(jnp.arange(L)[None, :])
        return x.astype(self.cfg.compute_dtype)

    def head(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        x = self.ln_f(x).astype(cfg.compute_dtype)
        if cfg.tie_embeddings:
            # Shared-table logits in compute dtype (bf16 MXU path),
            # sentinel rows sliced off — matching TransformerLM's tied
            # head exactly so cross-family parity is bitwise-testable.
            table = self.tok_emb.embedding.astype(cfg.compute_dtype)
            logits = jnp.einsum("...d,vd->...v", x, table)
            return logits[..., :cfg.vocab_size].astype(jnp.float32)
        return self.lm_head(x).astype(jnp.float32)

    def head_pieces(self, x: jax.Array):
        """(features, head matrix, bias, vocab axis) — the fused-CE
        contract (same as TransformerLM's features_only mode): the
        head matmul runs inside the loss, chunk by chunk, so the
        [mb, L, V] logits never materialize at the last stage."""
        cfg = self.cfg
        x = self.ln_f(x).astype(cfg.compute_dtype)
        if cfg.tie_embeddings:
            return x, self.tok_emb.embedding[:cfg.vocab_size], None, 0
        kernel, bias = self.lm_head(None)
        return x, kernel, bias, 1

    def __call__(self, tokens: jax.Array) -> jax.Array:  # init path only
        return self.head(self.embed(tokens))


class PipelinedLM:
    """Decoder/encoder LM with the block stack pipeline-parallel."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh,
                 num_microbatches: int = 4, extra_vocab: int = 0,
                 virtual_stages: int = 1):
        if cfg.tp_partitioning:
            raise ValueError(
                "pipelined variant needs tp_partitioning=False (flax "
                "DenseGeneral re-applies the TP constraint inside the "
                "pipe shard_map; see TransformerConfig.tp_partitioning)"
                " — TP names are re-attached to the stacked leaves by "
                "init() instead")
        if mesh.shape[AXIS_SEQ] > 1 and not cfg.causal:
            raise ValueError(
                "pipelined variant with mesh.seq > 1 needs causal=True"
                " (ring attention supports only the causal mask on a "
                "sharded seq axis; parallel.ring_attention)")
        if dict(mesh.shape).get("expert", 1) != 1:
            raise ValueError(
                "pipelined variant: mesh expert must be 1 — the "
                "stacked-leaf TP name table (_TP_SUFFIX) pins expert "
                "weights to the \"model\" axis; use mesh.model for EP "
                "with the pipeline")
        S = mesh.shape[AXIS_PIPE]
        if virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be >= 1, got {virtual_stages}")
        if cfg.n_layers % (S * virtual_stages):
            raise ValueError(
                f"{cfg.n_layers} layers not divisible by {S} stages"
                + (f" x {virtual_stages} virtual chunks"
                   if virtual_stages > 1 else ""))
        self.cfg = cfg
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.virtual_stages = virtual_stages
        self._shell = _Shell(cfg, extra_vocab)
        # use_flash=True: the Block keeps the mesh so the attention
        # dispatcher (ops.flash_attention.attention) can wrap the
        # Mosaic kernel in its own NESTED shard_map over the remaining
        # auto axes (data/model) — the pipe shard_map manualizes only
        # {"pipe"}, and a Mosaic call needs fully-manual axes. With
        # use_flash=False the Block sees no mesh and the XLA attention
        # path partitions under GSPMD as before. mesh.seq > 1 ALSO
        # needs the mesh regardless of flash: the Block's dispatch
        # routes seq-sharded activations to ring attention, whose own
        # shard_map nests over the remaining auto axes the same way
        # (parallel.ring_attention — the pipe x ring composition,
        # VERDICT r4 item 3).
        self._block = Block(cfg, mesh if (cfg.use_flash or
                                          mesh.shape[AXIS_SEQ] > 1)
                            else None)

    # -- flax-compatible surface -----------------------------------------

    def init(self, key: jax.Array, tokens: jax.Array,
             train: bool = False) -> Any:
        del train
        cfg = self.cfg
        k_shell, k_blocks = jax.random.split(key)
        shell_params = self._shell.init(k_shell, tokens)["params"]
        x = jnp.zeros((tokens.shape[0], tokens.shape[1], cfg.d_model),
                      cfg.compute_dtype)
        layer_keys = jax.random.split(k_blocks, cfg.n_layers)
        # Unbox inside the vmap: Block's TP partition metadata (rank-N
        # names) would be stale on the rank-N+2 stacked leaves — the
        # pipelined variant enforces model=seq=1, so dropping it is
        # sound; pipe-axis boxes are added below with full-rank names.
        pos = (jnp.arange(tokens.shape[1])[None, :]
               if cfg.pos_emb == "rope" else None)
        stacked = jax.vmap(lambda k: nn.meta.unbox(
            self._block.init(k, x, False,
                             positions=pos)["params"]))(layer_keys)
        staged = stack_stage_params(stacked,
                                    self.mesh.shape[AXIS_PIPE],
                                    virtual=self.virtual_stages)
        lead = 2 if self.virtual_stages == 1 else 3
        boxed = jax.tree_util.tree_map_with_path(
            lambda path, p: nn.Partitioned(
                p, names=(AXIS_PIPE,) + (None,) * (lead - 1)
                + _tp_names(path, p.ndim, lead)),
            staged)
        return {"params": {"shell": shell_params, "blocks": boxed}}

    def make_stage_fn(self, train: bool, with_rng: bool,
                      with_aux: bool = False):
        """The per-stage compute: scan this stage's blocks in order,
        folding the (mb, stage)-scoped key per layer so every
        (mb, stage, layer) dropout mask is distinct. Shared by the
        GPipe apply() and the 1F1B train step (train.pipeline_step).

        ``with_aux``: collect each MoE block's sown "moe_aux" values
        (models/moe.py AUX_NAMES) and return ``(y, aux_sums)`` — the
        pipeline schedules mask bubble ticks and total these across
        (stage, microbatch); without it the sows are silently dropped
        (flax no-ops sow on immutable collections), which is exactly
        the router-collapse trap this flag exists to close."""
        from tensorflow_distributed_tpu.models.moe import (
            AUX_NAMES, collect_aux)

        def stage_fn(stage_params, x_mb, key=None):
            lps = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
            # RoPE positions are microbatch-INVARIANT: microbatches
            # slice the batch dim, never the sequence, so every
            # (stage, microbatch) sees the same arange(L) — derivable
            # right here from the activation shape, no threading
            # through the schedule needed.
            pos = (jnp.arange(x_mb.shape[1])[None, :]
                   if self.cfg.pos_emb == "rope" else None)

            def one_layer(carry, xs):
                x, aux = carry
                layer_p, li = xs
                r = ({"dropout": jax.random.fold_in(key, li)}
                     if with_rng else None)
                if with_aux:
                    y, mut = self._block.apply(
                        {"params": layer_p}, x, train, rngs=r,
                        positions=pos, mutable=["moe_aux"])
                    layer_aux = collect_aux(mut["moe_aux"])
                    aux = {k: aux[k] + jnp.asarray(layer_aux[k],
                                                   jnp.float32)
                           for k in AUX_NAMES}
                else:
                    y = self._block.apply({"params": layer_p}, x, train,
                                          rngs=r, positions=pos)
                return (y, aux), None
            if self.cfg.remat:
                # --remat for the pipelined family: rematerialize each
                # block on backward (cfg.remat_policy as in
                # models/transformer.py), so activation memory per stage
                # is O(1) blocks instead of O(layers_per_stage).
                one_layer = jax.checkpoint(
                    one_layer,
                    policy=resolve_remat_policy(self.cfg.remat_policy))
            aux0 = ({k: jnp.zeros((), jnp.float32) for k in AUX_NAMES}
                    if with_aux else ())
            (y, aux), _ = jax.lax.scan(one_layer, (x_mb, aux0),
                                       (stage_params, jnp.arange(lps)))
            return (y, aux) if with_aux else y

        return stage_fn

    def embed(self, shell_params: Any, tokens: jax.Array) -> jax.Array:
        return self._shell.apply({"params": shell_params}, tokens,
                                 method="embed")

    def head(self, shell_params: Any, x: jax.Array) -> jax.Array:
        return self._shell.apply({"params": shell_params}, x,
                                 method="head")

    def head_pieces(self, shell_params: Any, x: jax.Array):
        return self._shell.apply({"params": shell_params}, x,
                                 method="head_pieces")

    def apply(self, variables: Any, tokens: jax.Array, *,
              train: bool = False, rngs: Optional[Any] = None,
              mutable: Any = (), features_only: bool = False):
        """Forward pass. ``mutable=["moe_aux"]`` (the flax collection
        surface train.tasks.make_moe_loss speaks) additionally returns
        the router losses collected THROUGH the pipeline schedule —
        normalized to per-layer-per-microbatch means so they compare
        exactly with the non-pipelined families' sown values."""
        # Normalize the flax-style mutable forms: str | bool | iterable.
        if isinstance(mutable, str):
            mutable = (mutable,)
        elif isinstance(mutable, bool):
            mutable = ("moe_aux",) if mutable else ()
        mutable = tuple(mutable)
        unsupported = set(mutable) - {"moe_aux"}
        if unsupported:
            # Fail fast: silently returning a bare array would make a
            # flax-style `out, mut = apply(...)` unpack split the batch
            # dim instead of erroring.
            raise ValueError(
                f"PipelinedLM.apply supports mutable=['moe_aux'] only; "
                f"got {sorted(unsupported)}")
        want_aux = "moe_aux" in mutable
        p = variables["params"]
        x = self.embed(p["shell"], tokens)
        use_dropout = bool(train and self.cfg.dropout_rate
                           and rngs and "dropout" in rngs)
        if want_aux and self.cfg.moe_experts <= 0:
            raise ValueError("mutable=['moe_aux'] needs moe_experts > 0")
        stage_fn = self.make_stage_fn(train, use_dropout,
                                      with_aux=want_aux)
        rng = rngs["dropout"] if use_dropout else None
        out = (self.head_pieces if features_only else self.head)
        V = self.virtual_stages
        # Interleaved layout ([S, V, lps, ...]): chunk group v is a
        # contiguous depth-S segment laid out one-chunk-per-device, so
        # the forward is V chained plain pipeline passes — correct for
        # eval/GPipe (the bubble-overlapped single-scan schedule lives
        # in interleaved_pipeline_value_and_grad, 1F1B only). Keys
        # fold per pass so no (mb, stage) pair repeats across chunks.
        groups = ([p["blocks"]] if V == 1 else
                  [jax.tree_util.tree_map(lambda q: q[:, v], p["blocks"])
                   for v in range(V)])
        if want_aux:
            aux_tot = None
            for v, gp in enumerate(groups):
                rv = (jax.random.fold_in(rng, v)
                      if rng is not None and V > 1 else rng)
                x, aux_sums = pipeline_apply(
                    stage_fn, gp, x, self.mesh,
                    self.num_microbatches, rng=rv, stage_aux=True)
                aux_tot = aux_sums if aux_tot is None else (
                    jax.tree_util.tree_map(lambda a, b: a + b, aux_tot,
                                           aux_sums))
            denom = self.cfg.n_layers * self.num_microbatches
            mut = {"moe_aux": {"pipeline": {
                k: (v / denom,) for k, v in aux_tot.items()}}}
            return out(p["shell"], x), mut
        for v, gp in enumerate(groups):
            rv = (jax.random.fold_in(rng, v)
                  if rng is not None and V > 1 else rng)
            x = pipeline_apply(stage_fn, gp, x, self.mesh,
                               self.num_microbatches, rng=rv)
        return out(p["shell"], x)


# The layer count pipelined_lm bumps tiny_config's n_layers=2 up to,
# so common stage counts (2, 4) divide it. Named so the auto-layout
# planner's model facts (analysis/planner/candidates.model_facts)
# prune pipe-axis shapes against the SAME number the scorer's real
# build slices into stages.
PIPELINED_TINY_LAYERS = 4


def pipelined_lm(mesh: Mesh, size: str = "tiny", causal: bool = True,
                 num_microbatches: int = 4, virtual_stages: int = 1,
                 **overrides) -> PipelinedLM:
    """Registry factory ("pipelined_lm"). Sizes: "tiny" (tests/CI) or
    "small" (GPT-2-small: 12L x 768d x 12H — the flagship config, run
    pipelined). ``num_microbatches`` is CLI-exposed as
    --pipeline-microbatches; ``virtual_stages`` as
    --pipeline-virtual-stages (config.TrainConfig)."""
    overrides["causal"] = causal
    overrides["tp_partitioning"] = False  # see TransformerConfig notes
    # Pallas flash attention works inside the pipe via a nested
    # shard_map (see PipelinedLM.__init__); default on like the rest
    # of the GPT family, opt out with use_flash=False.
    overrides.setdefault("use_flash", True)
    if size == "tiny":
        # tiny default (2) < common stage counts
        overrides.setdefault("n_layers", PIPELINED_TINY_LAYERS)
        cfg = tiny_config(**overrides)
    else:
        from tensorflow_distributed_tpu.models.transformer import (
            GPT2_SIZES, gpt2_small_config)
        if size not in GPT2_SIZES:
            raise ValueError(
                f"pipelined_lm size {size!r}; have "
                f"(tiny, {', '.join(GPT2_SIZES)})")
        cfg = gpt2_small_config(**{**GPT2_SIZES[size], **overrides})
    return PipelinedLM(cfg, mesh, num_microbatches,
                       virtual_stages=virtual_stages)
