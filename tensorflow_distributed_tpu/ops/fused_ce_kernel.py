"""Pallas TPU fused linear+cross-entropy ("flash CE").

The scan formulation (ops/fused_ce.py) already avoids the full
[T, V] logits tensor, but each [T, chunk] chunk still round-trips HBM:
the chunk matmul materializes, the reductions read it back, and the
backward recomputes it into another materialized chunk. This module is
the kernel form of the same math — the flash-attention treatment
applied to the vocabulary axis:

- **Forward**: one `pallas_call` over a (T/bt, V/bv) grid. Head-matrix
  blocks stream through VMEM while running (max, normalizer, gold
  logit, logit sum, argmax) accumulators live in VMEM scratch — a
  logits block exists only as an MXU output in VMEM, never in HBM.
  Emits per-token (ce, correct, lse); the [bt, bv] logits block is the
  only logits object that ever exists.
- **Backward**: custom VJP with two more kernels that recompute the
  logits block from the saved per-token lse — dx over the (T/bt, V/bv)
  grid accumulating across vocab blocks, dw/db over the transposed
  (V/bv, T/bt) grid accumulating across token blocks — exactly the
  dq / dkv split of the attention backward (ops/flash_attention.py).
- TPU grids execute sequentially with the last axis fastest, which is
  what makes scratch accumulation across the inner axis sound (same
  property the attention kernels rely on).
- Per-token vectors (targets, lse, coef, and the ce/correct/lse
  outputs) ride in [T, 8] buffers — tokens on the sublane axis, 8
  replicated lanes — the same layout trick the attention kernels use
  for lse: a flat [T] row is unmappable to a legal Mosaic tile.

Semantics match ops.losses.masked_ce_sums / ops.fused_ce.fused_ce_sums
(f32 statistics, first-max argmax, smoothing as the (1-eps)/eps-uniform
mixture); parity is pinned in tests/test_fused_ce_kernel.py,
interpret-mode on CPU like the other Pallas tests. No reference
counterpart: the reference's output layer is 10 classes
(mnist_python_m.py:196,205) — this exists for the LM families' 50k-row
heads.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tensorflow_distributed_tpu.ops.fused_ce import _zeros_cotangent

NEG_INF = -1e30  # large-finite; matches ops/flash_attention.py
INT_BIG = 2 ** 30
LANES = 8        # replication width for per-token rows (see docstring)


def _block_logits(x_ref, w_ref, b_ref, j, bv, vocab_size, w_vocab_axis):
    """Raw f32 logits for this (token, vocab) block pair + the column
    ids and the valid-column mask (cols past the real vocab are pad)."""
    x = x_ref[...]                                   # [bt, D]
    w = w_ref[...]                                   # [bv, D] or [D, bv]
    dims = ((((1,), (1,)), ((), ())) if w_vocab_axis == 0
            else (((1,), (0,)), ((), ())))
    logits = jax.lax.dot_general(x, w, dims,
                                 preferred_element_type=jnp.float32)
    logits = logits + b_ref[:1, :].astype(jnp.float32)
    colid = j * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    return logits, colid, colid < vocab_size


def _dlogits(logits, colid, valid, lse_col, t_col, coef_col, vocab_size,
             label_smoothing):
    """coef * (softmax - smoothed_onehot) for one block — the backward
    block math shared by the dx and dw kernels. lse/t/coef arrive as
    [bt, 1] columns."""
    s = jnp.where(valid, logits, NEG_INF)
    p = jnp.exp(s - lse_col)                         # pad cols -> 0
    onehot = (colid == t_col).astype(jnp.float32)
    d = p - (1.0 - label_smoothing) * onehot
    if label_smoothing:
        d = d - (label_smoothing / vocab_size) * valid.astype(jnp.float32)
    return d * coef_col


def _fwd_kernel(x_ref, w_ref, b_ref, t_ref, ce_ref, corr_ref, lse_ref,
                m_scr, l_scr, gold_scr, lsum_scr, bv_scr, bi_scr, *,
                bv, vocab_size, label_smoothing, w_vocab_axis):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        gold_scr[:] = jnp.zeros_like(gold_scr)
        lsum_scr[:] = jnp.zeros_like(lsum_scr)
        bv_scr[:] = jnp.full_like(bv_scr, NEG_INF)
        bi_scr[:] = jnp.full_like(bi_scr, -1)

    logits, colid, valid = _block_logits(x_ref, w_ref, b_ref, j, bv,
                                         vocab_size, w_vocab_axis)
    t_col = t_ref[:, :1]                             # [bt, 1] int32
    s = jnp.where(valid, logits, NEG_INF)

    # Online logsumexp over vocab blocks (the flash recurrence).
    m_prev = m_scr[:, :1]
    bmax = jnp.max(s, axis=-1, keepdims=True)        # [bt, 1]
    m_cur = jnp.maximum(m_prev, bmax)
    l_new = (l_scr[:, :1] * jnp.exp(m_prev - m_cur)
             + jnp.sum(jnp.exp(s - m_cur), axis=-1, keepdims=True))
    m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # Gold logit: at most one column matches the target.
    eq = jnp.logical_and(colid == t_col, valid)
    gold_new = gold_scr[:, :1] + jnp.sum(jnp.where(eq, logits, 0.0),
                                         axis=-1, keepdims=True)
    gold_scr[:] = jnp.broadcast_to(gold_new, gold_scr.shape)
    if label_smoothing:
        lsum_new = lsum_scr[:, :1] + jnp.sum(
            jnp.where(valid, logits, 0.0), axis=-1, keepdims=True)
        lsum_scr[:] = jnp.broadcast_to(lsum_new, lsum_scr.shape)

    # First-max argmax across blocks: strict > keeps the earlier
    # block's winner; within a block, the smallest max column wins.
    is_max = jnp.logical_and(s == bmax, valid)
    bidx = jnp.min(jnp.where(is_max, colid, INT_BIG), axis=-1,
                   keepdims=True)
    take = bmax > bv_scr[:, :1]
    bi_scr[:] = jnp.broadcast_to(jnp.where(take, bidx, bi_scr[:, :1]),
                                 bi_scr.shape)
    bv_scr[:] = jnp.broadcast_to(jnp.where(take, bmax, bv_scr[:, :1]),
                                 bv_scr.shape)

    @pl.when(j == nv - 1)
    def _():
        lse = m_scr[:, :1] + jnp.log(l_scr[:, :1])   # [bt, 1]
        gold = gold_scr[:, :1]
        if label_smoothing:
            gold = ((1.0 - label_smoothing) * gold
                    + (label_smoothing / vocab_size) * lsum_scr[:, :1])
        ce_ref[...] = jnp.broadcast_to(lse - gold, ce_ref.shape)
        corr_ref[...] = jnp.broadcast_to(
            (bi_scr[:, :1] == t_col).astype(jnp.float32), corr_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _dx_kernel(x_ref, w_ref, b_ref, t_ref, lse_ref, coef_ref, dx_ref,
               dx_scr, *, bv, vocab_size, label_smoothing,
               w_vocab_axis):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        dx_scr[:] = jnp.zeros_like(dx_scr)

    logits, colid, valid = _block_logits(x_ref, w_ref, b_ref, j, bv,
                                         vocab_size, w_vocab_axis)
    d = _dlogits(logits, colid, valid, lse_ref[:, :1], t_ref[:, :1],
                 coef_ref[:, :1], vocab_size, label_smoothing)
    w = w_ref[...]
    dims = ((((1,), (0,)), ((), ())) if w_vocab_axis == 0
            else (((1,), (1,)), ((), ())))
    dx_scr[:] += jax.lax.dot_general(d.astype(w.dtype), w, dims,
                                     preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _():
        dx_ref[...] = dx_scr[:].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, b_ref, t_ref, lse_ref, coef_ref,
               dw_ref, db_ref, dw_scr, db_scr, *, bv, vocab_size,
               label_smoothing, w_vocab_axis):
    i = pl.program_id(0)                             # vocab block
    j = pl.program_id(1)                             # token block (inner)
    nt = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        dw_scr[:] = jnp.zeros_like(dw_scr)
        db_scr[:] = jnp.zeros_like(db_scr)

    logits, colid, valid = _block_logits(x_ref, w_ref, b_ref, i, bv,
                                         vocab_size, w_vocab_axis)
    d = _dlogits(logits, colid, valid, lse_ref[:, :1], t_ref[:, :1],
                 coef_ref[:, :1], vocab_size, label_smoothing)
    x = x_ref[...]
    if w_vocab_axis == 0:                            # dw [bv, D]
        dw_scr[:] += jax.lax.dot_general(
            d.astype(x.dtype), x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:                                            # dw [D, bv]
        dw_scr[:] += jax.lax.dot_general(
            x, d.astype(x.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    # Every sublane row accumulates the same [1, bv] sum; read row 0.
    db_scr[:] += jnp.broadcast_to(
        jnp.sum(d, axis=0, keepdims=True), db_scr.shape)

    @pl.when(j == nt - 1)
    def _():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)
        db_ref[...] = db_scr[:]


def _pad_vocab_dim(w, bias, vocab_size, bv, w_vocab_axis):
    pad = (-vocab_size) % bv
    if pad:
        widths = [(0, 0)] * w.ndim
        widths[w_vocab_axis] = (0, pad)
        w = jnp.pad(w, widths)
        bias = jnp.pad(bias, (0, pad))
    return w, bias, vocab_size + pad


def _w_spec(D, bv, w_vocab_axis, outer="v"):
    """BlockSpec for the head matrix in either orientation. ``outer``
    names which grid axis walks the vocab blocks (fwd/dx grids are
    (token, vocab); the dw grid is (vocab, token))."""
    pick = (lambda i, j: j) if outer == "v" else (lambda i, j: i)
    if w_vocab_axis == 0:
        return pl.BlockSpec((bv, D), lambda i, j: (pick(i, j), 0))
    return pl.BlockSpec((D, bv), lambda i, j: (0, pick(i, j)))


def _lanes(v):
    """[T] -> [T, LANES] replicated (the mappable per-token layout)."""
    return jnp.broadcast_to(v[:, None], (v.shape[0], LANES))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def fused_ce_tokens(x, w, bias, targets, mask, vocab_size, bt, bv,
                    label_smoothing, w_vocab_axis, interpret):
    """Per-token (ce, correct) via the Pallas kernels.

    x: [T, D] (T % bt == 0, D % 128 == 0); w: head matrix, vocab dim on
    ``w_vocab_axis``; bias: [V] (callers pass zeros when the head has
    none — the kernel always adds it); targets/mask: [T]. Returns
    (ce [T] f32, correct [T] f32); reduce with the mask outside.
    Differentiable wrt x, w, bias — the cotangent of ce[t] (which the
    mask rides when the caller reduces sum(ce * mask)) scales that
    token's dlogits row.
    """
    ce, corr, _ = _fwd(x, w, bias, targets, vocab_size, bt, bv,
                       label_smoothing, w_vocab_axis, interpret)
    return ce, corr


def _fwd(x, w, bias, targets, vocab_size, bt, bv, label_smoothing,
         w_vocab_axis, interpret):
    T, D = x.shape
    wp, bp, vp = _pad_vocab_dim(w, bias, vocab_size, bv, w_vocab_axis)
    grid = (T // bt, vp // bv)
    kernel = functools.partial(
        _fwd_kernel, bv=bv, vocab_size=vocab_size,
        label_smoothing=label_smoothing, w_vocab_axis=w_vocab_axis)
    row = pl.BlockSpec((bt, LANES), lambda i, j: (i, 0))
    ce, corr, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, D), lambda i, j: (i, 0)),
            _w_spec(D, bv, w_vocab_axis),
            pl.BlockSpec((LANES, bv), lambda i, j: (0, j)),
            row,
        ],
        out_specs=[row, row, row],
        out_shape=[jax.ShapeDtypeStruct((T, LANES), jnp.float32)] * 3,
        scratch_shapes=[pltpu.VMEM((bt, 128), jnp.float32)] * 5
        + [pltpu.VMEM((bt, 128), jnp.int32)],
        interpret=interpret,
    )(x, wp, jnp.broadcast_to(bp[None], (LANES, vp)),
      _lanes(targets.astype(jnp.int32)))
    return ce[:, 0], corr[:, 0], lse[:, 0]


def _fused_ce_tokens_fwd(x, w, bias, targets, mask, vocab_size, bt, bv,
                         label_smoothing, w_vocab_axis, interpret):
    ce, corr, lse = _fwd(x, w, bias, targets, vocab_size, bt, bv,
                         label_smoothing, w_vocab_axis, interpret)
    return (ce, corr), (x, w, bias, targets, mask, lse)


def _fused_ce_tokens_bwd(vocab_size, bt, bv, label_smoothing,
                         w_vocab_axis, interpret, res, cots):
    x, w, bias, targets, mask, lse = res
    g_ce, _ = cots                                   # correct: metric only
    T, D = x.shape
    wp, bp, vp = _pad_vocab_dim(w, bias, vocab_size, bv, w_vocab_axis)
    row = pl.BlockSpec((bt, LANES), lambda i, j: (i, 0))
    common = dict(bv=bv, vocab_size=vocab_size,
                  label_smoothing=label_smoothing,
                  w_vocab_axis=w_vocab_axis)
    args = (x, wp, jnp.broadcast_to(bp[None], (LANES, vp)),
            _lanes(targets.astype(jnp.int32)), _lanes(lse),
            _lanes(g_ce.astype(jnp.float32)))

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, **common),
        grid=(T // bt, vp // bv),
        in_specs=[
            pl.BlockSpec((bt, D), lambda i, j: (i, 0)),
            _w_spec(D, bv, w_vocab_axis),
            pl.BlockSpec((LANES, bv), lambda i, j: (0, j)),
            row, row, row,
        ],
        out_specs=pl.BlockSpec((bt, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((T, D), x.dtype),
        scratch_shapes=[pltpu.VMEM((bt, D), jnp.float32)],
        interpret=interpret,
    )(*args)

    # Transposed grid: vocab outer, tokens inner (the dkv pattern).
    rowT = pl.BlockSpec((bt, LANES), lambda i, j: (j, 0))
    dw_shape = ((vp, D) if w_vocab_axis == 0 else (D, vp))
    dw_block = ((bv, D) if w_vocab_axis == 0 else (D, bv))
    dw_map = ((lambda i, j: (i, 0)) if w_vocab_axis == 0
              else (lambda i, j: (0, i)))
    dw, db = pl.pallas_call(
        functools.partial(_dw_kernel, **common),
        grid=(vp // bv, T // bt),
        in_specs=[
            pl.BlockSpec((bt, D), lambda i, j: (j, 0)),
            _w_spec(D, bv, w_vocab_axis, outer="i"),
            pl.BlockSpec((LANES, bv), lambda i, j: (0, i)),
            rowT, rowT, rowT,
        ],
        out_specs=[pl.BlockSpec(dw_block, dw_map),
                   pl.BlockSpec((LANES, bv), lambda i, j: (0, i))],
        out_shape=[jax.ShapeDtypeStruct(dw_shape, w.dtype),
                   jax.ShapeDtypeStruct((LANES, vp), jnp.float32)],
        scratch_shapes=[pltpu.VMEM(dw_block, jnp.float32),
                        pltpu.VMEM((LANES, bv), jnp.float32)],
        interpret=interpret,
    )(*args)

    if w_vocab_axis == 0:
        dw = dw[:vocab_size]
    else:
        dw = dw[:, :vocab_size]
    db = db[0, :vocab_size].astype(bias.dtype)
    return (dx, dw.astype(w.dtype), db,
            np.zeros(targets.shape, jax.dtypes.float0),
            _zeros_cotangent(mask))


fused_ce_tokens.defvjp(_fused_ce_tokens_fwd, _fused_ce_tokens_bwd)


DEFAULT_BT = 256
DEFAULT_BV = 2048


def kernel_supported(T: int, D: int, bt: int = DEFAULT_BT,
                     bv: int = DEFAULT_BV) -> bool:
    """Shape gate for the kernel path (else use the scan formulation,
    ops/fused_ce.py — same math, all shapes). D rides as a full block
    dim (legal at any size by dim-equality; 128 multiples are the
    fast layouts), so only sublane alignment constrains it."""
    bt = min(bt, T)
    return T % bt == 0 and bt % 8 == 0 and D % 8 == 0 and bv % 128 == 0


def fused_ce_sums_kernel(x: jax.Array, w: jax.Array,
                         bias: Optional[jax.Array], targets: jax.Array,
                         mask: jax.Array, vocab_size: int, *,
                         bt: int = DEFAULT_BT, bv: int = DEFAULT_BV,
                         label_smoothing: float = 0.0,
                         w_vocab_axis: int = 0,
                         interpret: Optional[bool] = None):
    """Drop-in for ops.fused_ce.fused_ce_sums on kernel-supported
    shapes: (ce_sum, correct, mask_sum), differentiable wrt x/w/bias.

    x: [..., D] — leading dims flatten to the token axis.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    D = x.shape[-1]
    T = x.size // D
    bt = min(bt, T)
    if not kernel_supported(T, D, bt, bv):
        raise ValueError(
            f"fused_ce kernel unsupported for T={T}, D={D} "
            f"(bt={bt}, bv={bv}); use ops.fused_ce.fused_ce_sums")
    xf = x.reshape(T, D)
    tf_ = targets.reshape(T).astype(jnp.int32)
    mf = mask.reshape(T).astype(jnp.float32)
    if bias is None:
        bias = jnp.zeros((vocab_size,), jnp.float32)
    ce, corr = fused_ce_tokens(xf, w, bias, tf_, mf, vocab_size, bt,
                               bv, label_smoothing, w_vocab_axis,
                               interpret)
    return jnp.sum(ce * mf), jnp.sum(corr * mf), jnp.sum(mf)
