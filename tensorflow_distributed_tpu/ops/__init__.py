"""Numerics: losses, metrics, and Pallas TPU kernels for the hot ops."""

from tensorflow_distributed_tpu.ops.losses import (  # noqa: F401
    accuracy,
    softmax_cross_entropy,
)
