"""Pallas TPU flash attention: fused, blockwise, O(L) memory.

The reference computes no attention at all (its model is a LeNet CNN,
mnist_python_m.py:104-128) and leaves every op kernel to stock
TensorFlow C++ (SURVEY.md N11). This framework's sequence family
(models/transformer.py) is TPU-first, and attention is its hot op —
so it gets a hand-written Pallas kernel rather than leaning on XLA's
generic fusion:

- **Forward**: one `pallas_call` over a (batch*heads, Lq/bq, Lk/bk)
  grid. K/V blocks stream through VMEM while a running
  (max, sum, weighted-V) streaming-softmax accumulator lives in VMEM
  scratch — the full [L, L] score matrix never exists in HBM.
  Softmax statistics in f32; both matmuls hit the MXU with
  `preferred_element_type=f32`.
- **Backward**: custom VJP with two more Pallas kernels (dq over the
  q-block grid; dk/dv over the k-block grid) that recompute scores
  blockwise from the saved logsumexp instead of storing probabilities
  — the standard flash-attention memory trade, expressed natively.
- TPU grids execute sequentially with the last axis fastest, which is
  what makes scratch accumulation across the inner K (resp. Q) axis
  sound.

On non-TPU backends the kernels run under `interpret=True` (tests) or
callers use `parallel.ring_attention.full_attention` (the XLA oracle).
Causal masking is applied in-kernel, and fully-masked blocks are
SKIPPED: TPU grids are rectangular and execute every step, so the
skip is expressed as (a) a `pl.when` predicate around the compute body
— Mosaic emits real branches, the MXU never sees the masked block —
and (b) an index_map that re-points the skipped step's K/V (resp.
Q/dO) BlockSpec at an already-visited block, so the pipeline issues no
DMA for it either. Net: causal attention pays ~half the full-grid
FLOPs (the lower triangle plus the diagonal blocks), in all three
kernels (fwd, dq, dk/dv).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-finite: avoids inf-inf=nan in masked rows


def window_keep(rows, cols, window=0):
    """THE (row - window, row] causal-band predicate — the single
    construction shared by the kernel mask below, the XLA-oracle
    dispatcher path (attention()), and the decode-cache mask
    (models/transformer.py). window 0 = unlimited history."""
    keep = cols <= rows
    if window:
        keep = jnp.logical_and(keep, cols > rows - window)
    return keep


def window_bias(rows, cols, window=0):
    """Additive-bias form of window_keep ([1, Lq, Lk]-broadcastable,
    NEG_INF outside the band) — the one bias construction shared by
    the XLA-oracle dispatcher path and the decode-cache mask."""
    return jnp.where(window_keep(rows, cols, window), 0.0,
                     float(NEG_INF))[None]


def _causal_mask(s, i_q, i_k, bq, bk, window=0):
    """Causal mask, optionally sliding-window (window_keep)."""
    rows = i_q * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = i_k * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(window_keep(rows, cols, window), s, NEG_INF)


# Causal block-skip helpers. A (q-block i, k-block j) pair is needed iff
# its mask isn't all-False: the q block's last row i*bq + bq - 1 must
# reach the k block's first column j*bk — and under a sliding window
# the k block's last column j*bk + bk - 1 must still be inside the
# OLDEST row's window (row i*bq sees columns > i*bq - window). The
# index_map twins re-point skipped steps at a needed block so the
# revisit costs no DMA (Pallas only copies when the block index
# changes); under a window the inner index clamps into the needed
# band [lo, hi] — steps before lo prefetch block lo, steps after hi
# hold block hi.

def _kv_needed(i, j, bq, bk, window=0):
    need = j * bk <= i * bq + (bq - 1)
    if window:
        # Newest row of the q block is i*bq + bq - 1; its window spans
        # cols > i*bq + bq - 1 - window... but the OLDEST surviving
        # col across the block's rows comes from the oldest row i*bq:
        # cols > i*bq - window.
        need = jnp.logical_and(need, j * bk + (bk - 1) > i * bq - window)
    return need


def _causal_kv_map(bq, bk, window=0):
    def imap(b, i, j):
        hi = (i * bq + bq - 1) // bk
        if window:
            lo = jnp.maximum(i * bq - window + 1, 0) // bk
            return (b, jnp.clip(j, lo, hi), 0)
        return (b, jnp.minimum(j, hi), 0)
    return imap


def _q_needed(i, j, bq, bk, window=0):
    """dkv grid: i is the k-block index, j the q-block index."""
    need = j * bq + (bq - 1) >= i * bk
    if window:
        # Oldest col of this k block is i*bk; rows that still see it
        # satisfy row < i*bk + window — the newest such row bounds the
        # needed q blocks from above via the block's oldest row j*bq.
        need = jnp.logical_and(need,
                               j * bq < i * bk + (bk - 1) + window)
    return need


def _causal_q_map(bq, bk, window=0):
    def imap(b, i, j):
        lo = (i * bk) // bq
        if window:
            hi = (i * bk + bk - 2 + window) // bq
            return (b, jnp.clip(j, lo, hi), 0)
        return (b, jnp.maximum(j, lo), 0)
    return imap


# ---------------------------------------------------------------- forward

def _stream_softmax_step(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                         i, j, scale, causal, bq, bk, window=0):
    """One K,V block folded into the (m, l, acc) VMEM accumulators —
    the streaming-softmax body shared by the normalized and partial
    forward kernels. Runs under the causal block-skip predicate."""

    def compute():
        q = q_ref[0]                               # [bq, D]
        k = k_ref[0]                               # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, j, bq, bk, window)

        m_prev = m_scr[:, :1]                      # [bq, 1] f32
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                     # [bq, bk] f32
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip fully-masked K blocks (above the diagonal, and past
        # the window horizon) — a real branch, not predicated
        # arithmetic: the MXU work is not done.
        pl.when(_kv_needed(i, j, bq, bk, window))(compute)
    else:
        compute()


def _p_and_ds(q, k, v, do, row_sub, row_add, i_q, i_k, scale, causal,
              bq, bk, window=0):
    """Backward-pass block math shared by all four bwd kernels:
    p = exp(s - row_sub) and ds = p * (do.v^T + row_add) * scale.
    Normalized kernels pass (lse, -delta); partial kernels (m, +dl)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        s = _causal_mask(s, i_q, i_k, bq, bk, window)
    p = jnp.exp(s - row_sub)                       # [bq, bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return p, p * (dp + row_add) * scale


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk,
                window=0):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    _stream_softmax_step(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                         i, j, scale, causal, bq, bk, window)

    @pl.when(j == nk - 1)
    def _():
        l_final = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / l_final).astype(o_ref.dtype)
        lse = m_scr[:, :1] + jnp.log(l_final)      # [bq, 1]
        # lse rides in a [BH, L, 8] buffer: Mosaic requires the last two
        # block dims to divide (8, 128) or equal the array dims, so a
        # flat [BH, L] row output is unmappable; 8 lanes of replication
        # is the cheapest legal layout (the stock jax kernel uses 128).
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, causal, bq, bk, interpret, window=0):
    BH, L, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    grid = (BH, L // bq, Lk // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, window=window)
    kv_map = _causal_kv_map(bq, bk, window) if causal else (
        lambda b, i, j: (b, j, 0))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bk, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, L, D), q.dtype),
            jax.ShapeDtypeStruct((BH, L, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# --------------------------------------------------------------- backward

def _delta(do, out):
    """rowsum(dO * O) recomputed blockwise — cheaper than materializing
    a lane-replicated [BH, L, 8] delta buffer in HBM."""
    return jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1, keepdims=True)        # [bq, 1]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref,
               dq_scr, *, scale, causal, bq, bk, window=0):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _p_and_ds(q, k, v, do, lse_ref[0][:, :1],
                          -_delta(do, o_ref[0]), i, j, scale, causal,
                          bq, bk, window)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_kv_needed(i, j, bq, bk, window))(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                bq, bk, window=0):
    i = pl.program_id(1)                           # k-block index
    j = pl.program_id(2)                           # q-block index (inner)
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p, ds = _p_and_ds(q, k, v, do, lse_ref[0][:, :1],
                          -_delta(do, o_ref[0]), j, i, scale, causal,
                          bq, bk, window)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # Skip q blocks strictly above this k block's diagonal (and
        # past the window horizon below it).
        pl.when(_q_needed(i, j, bq, bk, window))(compute)
    else:
        compute()

    @pl.when(j == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd(q, k, v, out, lse, do, causal, bq, bk, interpret, window=0):
    BH, L, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / (D ** 0.5)

    kv_map = _causal_kv_map(bq, bk, window) if causal else (
        lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, window=window),
        grid=(BH, L // bq, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, out, lse)

    q_map = _causal_q_map(bq, bk, window) if causal else (
        lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, window=window),
        grid=(BH, Lk // bk, L // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bq, 8), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, out, lse)
    return dq, dk, dv


# ----------------------------------------------- partial-softmax variant
# Ring attention's building block (parallel.ring_attention): one Q-block
# vs one K,V-block PARTIAL attention returning the streaming-softmax
# triple (m = row max, l = exp-sum, o = unnormalized weighted V) that
# the ring merges across steps. Same blocking/VMEM scheme as the main
# kernel; the only differences are (a) o is written UNnormalized in f32
# and (b) m and l are emitted instead of the folded lse.
#
# VJP convention: m is the numerical stabilizer of the streaming
# softmax — the merged result is invariant to it — so it is treated as
# stop-gradient (exactly like jax.nn.softmax's max-shift). With
# p = exp(s - m):   dl/ds_ij = p_ij,   do_i/ds_ij = p_ij * v_j
# =>  ds_ij = p_ij * (do_i . v_j + dl_i),  dq = scale * ds @ k,
#     dk = scale * ds^T @ q,  dv = p^T @ do.
# These mirror _dq_kernel/_dkv_kernel with rowsum(do*o) replaced by
# the incoming -dl cotangent (delta there IS the normalized-case dl).


def _fwd_partial_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                        m_scr, l_scr, acc_scr, *, scale, causal, bq, bk):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    _stream_softmax_step(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                         i, j, scale, causal, bq, bk)

    @pl.when(j == nk - 1)
    def _():
        o_ref[0] = acc_scr[:]                      # UNnormalized, f32
        m_ref[0] = jnp.broadcast_to(m_scr[:, :1], m_ref.shape[1:])
        l_ref[0] = jnp.broadcast_to(l_scr[:, :1], l_ref.shape[1:])


def _fwd_partial(q, k, v, causal, bq, bk, interpret):
    BH, L, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    kv_map = _causal_kv_map(bq, bk) if causal else (
        lambda b, i, j: (b, j, 0))
    return pl.pallas_call(
        functools.partial(_fwd_partial_kernel, scale=scale,
                          causal=causal, bq=bq, bk=bk),
        grid=(BH, L // bq, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bk, D), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, L, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, L, 8), jnp.float32),
            jax.ShapeDtypeStruct((BH, L, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _dq_partial_kernel(q_ref, k_ref, v_ref, do_ref, dl_ref, m_ref,
                       dq_ref, dq_scr, *, scale, causal, bq, bk):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        _, ds = _p_and_ds(q, k, v, do, m_ref[0][:, :1],
                          dl_ref[0][:, :1], i, j, scale, causal, bq, bk)
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_kv_needed(i, j, bq, bk))(compute)
    else:
        compute()

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_partial_kernel(q_ref, k_ref, v_ref, do_ref, dl_ref, m_ref,
                        dk_ref, dv_ref, dk_scr, dv_scr, *,
                        scale, causal, bq, bk):
    i = pl.program_id(1)                           # k-block index
    j = pl.program_id(2)                           # q-block index
    nq = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def compute():
        q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
        p, ds = _p_and_ds(q, k, v, do, m_ref[0][:, :1],
                          dl_ref[0][:, :1], j, i, scale, causal, bq, bk)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_q_needed(i, j, bq, bk))(compute)
    else:
        compute()

    @pl.when(j == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_partial(q, k, v, m, do, dl, causal, bq, bk, interpret):
    BH, L, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    kv_map = _causal_kv_map(bq, bk) if causal else (
        lambda b, i, j: (b, j, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_partial_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk),
        grid=(BH, L // bq, Lk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bk, D), kv_map),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, L, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, dl, m)

    q_map = _causal_q_map(bq, bk) if causal else (
        lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_partial_kernel, scale=scale,
                          causal=causal, bq=bq, bk=bk),
        grid=(BH, Lk // bk, L // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), q_map),
            pl.BlockSpec((1, bq, 8), q_map),
            pl.BlockSpec((1, bq, 8), q_map),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Lk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, dl, m)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_partial(q, k, v, causal, bq, bk, interpret):
    return _fwd_partial(q, k, v, causal, bq, bk, interpret)


def _flash_partial_fwd(q, k, v, causal, bq, bk, interpret):
    o, m, l = _fwd_partial(q, k, v, causal, bq, bk, interpret)
    return (o, m, l), (q, k, v, m)


def _flash_partial_bwd(causal, bq, bk, interpret, res, cots):
    q, k, v, m = res
    do, _dm, dl = cots  # m is the stop-grad stabilizer (see above)
    return _bwd_partial(q, k, v, m, do.astype(jnp.float32), dl, causal,
                        bq, bk, interpret)


_flash_partial.defvjp(_flash_partial_fwd, _flash_partial_bwd)


def flash_attention_partial(q: jax.Array, k: jax.Array, v: jax.Array, *,
                            causal: bool = False, block_q: int = 1024,
                            block_k: int = 1024,
                            interpret: Optional[bool] = None):
    """Partial (unnormalized) blockwise attention for the ring path.

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]. Returns the streaming-
    softmax partials in ``parallel.ring_attention._block_attend``'s
    layout: (m [B,H,Lq] f32, l [B,H,Lq] f32, o [B,Lq,H,D] f32 —
    UNnormalized weighted V). Differentiable (custom VJP, Pallas both
    ways). ``causal=True`` applies the in-block triangular mask (the
    ring's diagonal blocks, where q and k share global offsets).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, L, H, D = q.shape
    Lk = k.shape[1]
    bq, bk = min(block_q, L), min(block_k, Lk)
    if L % bq or Lk % bk:
        raise ValueError(
            f"flash_attention_partial: seq lens ({L}, {Lk}) must "
            f"divide the clamped blocks ({bq}, {bk}); see supported()")

    def pack(x):
        n = x.shape[1]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, n,
                                                      x.shape[3])

    o, m, l = _flash_partial(pack(q), pack(k), pack(v), causal, bq, bk,
                             interpret)
    o = jnp.transpose(o.reshape(B, H, L, D), (0, 2, 1, 3))
    return m[..., 0].reshape(B, H, L), l[..., 0].reshape(B, H, L), o


# ------------------------------------------------------------ public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, bq, bk, interpret, window):
    out, _ = _fwd(q, k, v, causal, bq, bk, interpret, window)
    return out


def _flash_fwd(q, k, v, causal, bq, bk, interpret, window):
    out, lse = _fwd(q, k, v, causal, bq, bk, interpret, window)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, bq, bk, interpret, window, res, do):
    q, k, v, out, lse = res
    return _bwd(q, k, v, out, lse, do, causal, bq, bk, interpret,
                window)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, window: int = 0,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused blockwise attention. q,k,v: [B, L, H, D] -> [B, L, H, D].

    Differentiable (custom VJP, Pallas both ways). Block sizes clamp to
    the sequence lengths; lengths must divide the (clamped) blocks —
    `supported()` gates the dispatcher. Defaults (1024, 1024) won a
    block-size sweep on one v5e chip (B=4 H=8 D=64 bf16, L=1k..8k) for
    both causal and full; with the causal block skip they measure
    1.20x/1.42x faster than the full-grid kernel at L=4096/8192 fwd
    (1.28x/1.50x fwd+bwd), trending to the asymptotic 2x as L grows.
    Recorded end-to-end evidence: LMBENCH_r03.json at the repo root —
    GPT-2-small training with this kernel sustains 46.8% MFU and a
    1.57x step-level speedup over the XLA attention path
    (benchmarks/lm_perf.py reproduces it).
    `interpret=None` auto-selects interpreter mode off-TPU so the same
    kernel is testable on the 8-device CPU mesh (SURVEY.md §4).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if window and not causal:
        raise ValueError("window attention requires causal=True "
                         "(sliding window over past positions)")
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    B, L, H, D = q.shape
    Lk = k.shape[1]
    block_q = min(block_q, L)
    block_k = min(block_k, Lk)
    if L % block_q or Lk % block_k:
        # The grid would silently skip the ragged tail rows (whose
        # output buffer is uninitialized memory) — refuse instead.
        raise ValueError(
            f"flash_attention: seq lens ({L}, {Lk}) must divide the "
            f"clamped blocks ({block_q}, {block_k}); see supported()")

    def pack(x):
        n = x.shape[1]
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, n, x.shape[3])

    out = _flash(pack(q), pack(k), pack(v), causal, block_q, block_k,
                 interpret, window)
    return jnp.transpose(out.reshape(B, H, L, D), (0, 2, 1, 3))


def supported(L: int, Lk: int, D: int, block_q: int = 1024,
              block_k: int = 1024) -> bool:
    """Whether the Pallas kernel handles these shapes (else use the
    XLA path, parallel.ring_attention.full_attention)."""
    bq, bk = min(block_q, L), min(block_k, Lk)
    return (L % bq == 0 and Lk % bk == 0 and bq % 8 == 0 and bk % 8 == 0
            and D <= 256 and D % 8 == 0)


def use_flash(L: int, Lk: int, D: int) -> bool:
    """The ONE flash-dispatch gate, shared by the single-shard
    dispatcher (attention) and the ring path (_partial_attend): TPU
    backend (or TFD_FLASH_INTERPRET=1 forcing interpreter mode
    off-TPU, for CPU-mesh tests of the exact TPU code path) and
    kernel-supported shapes."""
    import os

    on_tpu = jax.default_backend() == "tpu"
    force = os.environ.get("TFD_FLASH_INTERPRET", "") == "1"
    return (on_tpu or force) and supported(L, Lk, D)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              mask: Optional[jax.Array] = None, *,
              causal: bool = False, window: int = 0, mesh=None,
              allow_flash: bool = True) -> jax.Array:
    """Dispatcher for the single-shard attention path: the Pallas
    kernel on TPU when shapes allow, the XLA oracle otherwise.
    (Ring attention owns the seq-sharded path.)

    ``mesh``: when the surrounding step is GSPMD-partitioned over a
    multi-device mesh, the Mosaic custom call has no partitioning rule
    of its own, so the kernel is wrapped in a shard_map over the
    (batch="data", heads="model") axes (+ "expert", where activations
    are replicated) — each device runs the kernel on its local shard;
    no cross-device comms are needed because batch and heads are
    embarrassingly parallel in attention. The shard_map names only
    those axes, NOT "pipe": inside the pipelined family's pipe-manual
    shard_map this nests as a partial manualization of the remaining
    auto axes, which is what lets the Mosaic kernel run inside the
    pipeline ("seq" stays auto and is 1 on every path that reaches
    flash — ring attention owns seq > 1).

    Setting TFD_FLASH_INTERPRET=1 forces this flash path off-TPU with
    the interpreter, so tests can exercise the full nested-shard_map
    structure on the 8-device CPU mesh.
    """
    import os

    from tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_DATA, AXIS_EXPERT, AXIS_MODEL)
    from tensorflow_distributed_tpu.parallel.ring_attention import (
        full_attention)
    if window and not causal:
        # Same check flash_attention() makes — the XLA path must not
        # silently drop the window for non-causal configs.
        raise ValueError("window attention requires causal=True "
                         "(sliding window over past positions)")
    B, L, H, D = q.shape
    if allow_flash and mask is None and use_flash(L, k.shape[1], D):
        from jax.sharding import PartitionSpec as P
        spec = P(AXIS_DATA, None, AXIS_MODEL, None)
        kernel = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=causal, window=window)
        ctx = jax.sharding.get_abstract_mesh()
        if ctx.manual_axes:
            # Inside an enclosing shard_map (the pipelined family's
            # pipe-manual region): Mosaic refuses to lower while ANY
            # axis is still auto — even a size-1 one — so nest a
            # shard_map over every remaining auto axis, handing it the
            # CONTEXT abstract mesh (the one whose "pipe" is already
            # Manual), not the concrete mesh. "seq" is always 1 on the
            # flash path (ring attention owns seq > 1), so leaving it
            # out of the specs replicates correctly.
            remaining = set(ctx.axis_names) - set(ctx.manual_axes)
            return jax.shard_map(
                kernel, mesh=ctx, in_specs=(spec, spec, spec),
                out_specs=spec, axis_names=remaining,
                check_vma=False)(q, k, v)
        if mesh is None or all(
                mesh.shape[a] == 1
                for a in (AXIS_DATA, AXIS_MODEL, AXIS_EXPERT)):
            return flash_attention(q, k, v, causal=causal,
                                   window=window)
        # GSPMD-partitioned step: fully-manual shard_map over the mesh;
        # batch and heads are embarrassingly parallel, no comms.
        return jax.shard_map(
            kernel, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_vma=False)(q, k, v)
    if causal:
        cmask = window_bias(jnp.arange(L)[:, None],
                            jnp.arange(k.shape[1])[None, :], window)
        mask = cmask if mask is None else mask + cmask
    return full_attention(q, k, v, mask)
