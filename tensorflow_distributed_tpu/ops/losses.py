"""Loss and metric math.

Parity targets in the reference:
- loss: ``tf.reduce_mean(tf.nn.softmax_cross_entropy_with_logits(pred, y))``
  with one-hot labels (mnist_python_m.py:205; mnist_single.py:94).
- metric: argmax-equality accuracy (mnist_python_m.py:206-207;
  mnist_single.py:97-98).

Computed in float32 regardless of the model's compute dtype — softmax
log-sum-exp in bf16 loses enough mantissa to visibly bend training curves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _smoothed_gold(logits: jax.Array, gold: jax.Array,
                   label_smoothing: float) -> jax.Array:
    """Replace the one-hot target term with the smoothed mixture
    (1-eps)*onehot + eps*uniform: CE becomes logz - [(1-eps)*gold +
    (eps/V)*sum(logits)] — same gather, one extra reduction, no
    materialized [.., V] target tensor."""
    if not label_smoothing:
        return gold
    v = logits.shape[-1]
    return ((1.0 - label_smoothing) * gold
            + (label_smoothing / v) * jnp.sum(logits, axis=-1))


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array,
                          label_smoothing: float = 0.0) -> jax.Array:
    """Mean softmax cross-entropy; ``labels`` are int class ids.

    The reference fed one-hot labels; integer labels with a take-along
    gather are the same math with one less materialized [B,10] tensor.
    ``label_smoothing``: standard (1-eps)/eps-uniform target mixture.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    gold = _smoothed_gold(logits, gold, label_smoothing)
    return jnp.mean(logz - gold)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions equal to labels
    (mnist_python_m.py:206-207)."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))


def masked_ce_sums(logits: jax.Array, targets: jax.Array,
                   mask: jax.Array, label_smoothing: float = 0.0):
    """UNNORMALIZED masked-CE pieces: (ce_sum, correct_sum, mask_sum).

    The building block shared by the mean-style losses below and the
    1F1B pipeline's per-microbatch accumulation (parallel.pipeline),
    which must sum pieces across microbatches and divide by the GLOBAL
    mask count once — normalizing per microbatch would silently
    reweight whenever mask counts differ.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    gold = _smoothed_gold(logits, gold, label_smoothing)
    mask = mask.astype(jnp.float32)
    ce_sum = jnp.sum((logz - gold) * mask)
    pred = jnp.argmax(logits, axis=-1)
    correct = jnp.sum((pred == targets).astype(jnp.float32) * mask)
    return ce_sum, correct, jnp.sum(mask)


def masked_softmax_cross_entropy(logits: jax.Array, targets: jax.Array,
                                 mask: jax.Array,
                                 label_smoothing: float = 0.0) -> jax.Array:
    """Mean cross-entropy over masked positions only (the MLM objective;
    no reference counterpart — the reference has no sequence models).

    logits: [B, L, V]; targets: [B, L] ints; mask: [B, L] {0,1}.
    """
    ce_sum, _, n = masked_ce_sums(logits, targets, mask, label_smoothing)
    return ce_sum / jnp.maximum(n, 1.0)


def masked_accuracy(logits: jax.Array, targets: jax.Array,
                    mask: jax.Array) -> jax.Array:
    _, correct, n = masked_ce_sums(logits, targets, mask)
    return correct / jnp.maximum(n, 1.0)
