"""Loss and metric math.

Parity targets in the reference:
- loss: ``tf.reduce_mean(tf.nn.softmax_cross_entropy_with_logits(pred, y))``
  with one-hot labels (mnist_python_m.py:205; mnist_single.py:94).
- metric: argmax-equality accuracy (mnist_python_m.py:206-207;
  mnist_single.py:97-98).

Computed in float32 regardless of the model's compute dtype — softmax
log-sum-exp in bf16 loses enough mantissa to visibly bend training curves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy; ``labels`` are int class ids.

    The reference fed one-hot labels; integer labels with a take-along
    gather are the same math with one less materialized [B,10] tensor.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    return jnp.mean(logz - gold)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions equal to labels
    (mnist_python_m.py:206-207)."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))


def masked_softmax_cross_entropy(logits: jax.Array, targets: jax.Array,
                                 mask: jax.Array) -> jax.Array:
    """Mean cross-entropy over masked positions only (the MLM objective;
    no reference counterpart — the reference has no sequence models).

    logits: [B, L, V]; targets: [B, L] ints; mask: [B, L] {0,1}.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    per_tok = (logz - gold) * mask
    return jnp.sum(per_tok) / jnp.maximum(jnp.sum(mask), 1.0)


def masked_accuracy(logits: jax.Array, targets: jax.Array,
                    mask: jax.Array) -> jax.Array:
    pred = jnp.argmax(logits, axis=-1)
    hit = (pred == targets).astype(jnp.float32) * mask
    return jnp.sum(hit) / jnp.maximum(jnp.sum(mask), 1.0)
