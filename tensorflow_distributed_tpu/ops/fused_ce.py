"""Fused (vocab-chunked) linear + softmax cross-entropy.

The dense LM loss path materializes the full ``[B, L, V]`` logits tensor
twice per step — once in the forward (the head matmul's output) and once
in the backward (``softmax - onehot``). At GPT-2-small shapes (batch 8,
seq 1024, vocab 50257) that is ~825 MB of bf16 per materialization, pure
HBM traffic the MXU waits on. No reference counterpart — the reference's
output layer is 10 classes (`mnist_python_m.py:196,205`), where none of
this matters; it exists for the LM families' 50k-row heads.

This op fuses the head matmul into the loss with an **online softmax
over vocabulary chunks** (the same running (m, l) recurrence the flash
attention kernels use over key blocks, ops/flash_attention.py): the
forward scans vocab chunks of the head matrix, keeping only the running
max / normalizer / gold-logit / argmax accumulators (all ``[B, L]``),
and the custom-VJP backward **recomputes** each chunk's logits to form
its slice of ``softmax - onehot`` on the fly. Peak logits memory drops
from ``[B, L, V]`` to ``[B, L, chunk]``; full logits are never written.

Chunking over *vocab* (not tokens) is the SPMD-friendly choice: the
batch/seq dims — the ones sharded over the ``data``/``seq`` mesh axes —
pass through untouched, so under pjit every device simply runs the same
chunk loop on its own activation shard; no resharding, no collectives
beyond the loss reductions that were already there.

**Vocab-parallel form** (``mesh.model > 1``): the Megatron
vocab-parallel cross-entropy, SPMD-native. Each TP rank scans only its
own vocab shard of the head matrix and keeps PARTIAL per-token stats;
the global softmax statistics come from one ``pmax`` (running max) and
three ``psum``s (normalizer, gold logit, smoothing sum) over the
``model`` axis, plus a ``pmin`` tie-break for the first-max argmax.
The hand-written backward recomputes each rank's chunk logits against
the GLOBAL logsumexp and psums the feature gradient; head-shard grads
stay local. This is what lets the fused loss compose with tensor
parallelism and the Megatron vocab-sharded embedding (shard_vocab).

Semantics match ``ops.losses.masked_ce_sums`` exactly (unnormalized
(ce_sum, correct, mask_sum) pieces, f32 statistics, label smoothing as
the (1-eps)/eps-uniform target mixture); parity — values and gradients
— is pinned in tests/test_fused_ce.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

INT_BIG = 2 ** 30
# Large-finite stand-in for -inf in the running-max init (matches
# ops/fused_ce_kernel.py's NEG_INF). A TRUE -inf init NaNs the online
# normalizer for a shard whose every column is padding (the
# vocab-parallel form with vocab_size < rows available to a rank):
# l*exp(m - new_m) = 0*exp(-inf - (-inf)). With a finite init the
# degenerate shard cleanly yields (m=NEG_INF, l=0), which the cross-
# rank combine weights to zero.
NEG_INF = -1e30


def _zeros_cotangent(a):
    """Symbolic-zero cotangent with the type AD expects for ``a``:
    float0 for non-inexact primals (bool/int masks — masked_ce_sums
    accepts them via astype), dense zeros otherwise."""
    if jnp.issubdtype(jnp.result_type(a), jnp.inexact):
        return jnp.zeros_like(a)
    return np.zeros(np.shape(a), jax.dtypes.float0)


def _pad_vocab(w: jax.Array, bias: Optional[jax.Array], rows: int,
               chunk: int, w_vocab_axis: int):
    """Zero-pad the vocab dim from ``rows`` up to a chunk multiple so
    every scan step slices a full, non-clamped chunk (dynamic_slice
    clamps out-of-range starts, which would silently alias the last
    rows)."""
    pad = (-rows) % chunk
    if pad:
        widths = [(0, 0)] * w.ndim
        widths[w_vocab_axis] = (0, pad)
        w = jnp.pad(w, widths)
        if bias is not None:
            bias = jnp.pad(bias, (0, pad))
    return w, bias, rows + pad


def _chunk_logits(x: jax.Array, w: jax.Array, bias: Optional[jax.Array],
                  c0: jax.Array, chunk: int, local_rows,
                  col_offset, vocab_size: int,
                  w_vocab_axis: int) -> Tuple[jax.Array, jax.Array]:
    """Logits for LOCAL vocab columns [c0, c0+chunk) in f32. A column
    is valid iff it is a real row of this shard (< local_rows — per-
    rank chunk padding is not) AND its GLOBAL id (col_offset + local
    id) is a real vocab entry. Invalid columns read -inf. Returns
    (logits [..., chunk], valid [chunk] bool)."""
    wc = jax.lax.dynamic_slice_in_dim(w, c0, chunk, axis=w_vocab_axis)
    wc = wc.astype(x.dtype)
    eq = "...d,cd->...c" if w_vocab_axis == 0 else "...d,dc->...c"
    logits = jnp.einsum(eq, x, wc,
                        preferred_element_type=jnp.float32)
    if bias is not None:
        bc = jax.lax.dynamic_slice_in_dim(bias, c0, chunk, axis=0)
        logits = logits + bc.astype(jnp.float32)
    local_col = c0 + jnp.arange(chunk)
    valid = jnp.logical_and(local_col < local_rows,
                            col_offset + local_col < vocab_size)
    logits = jnp.where(valid, logits, -jnp.inf)
    return logits, valid


def _scan_stats(x, wp, bp, targets, n_chunks, chunk, local_rows,
                col_offset, vocab_size, label_smoothing, w_vocab_axis):
    """The forward chunk scan: per-token partial stats over this head
    (shard). Returns (m, l, gold, lsum, best_v, best_i) — best_i in
    GLOBAL vocab ids (-1 where this shard saw nothing). The caller
    finishes locally (single rank) or combines across the model axis
    (vocab-parallel)."""
    bshape = targets.shape
    targets = targets.astype(jnp.int32)

    def body(carry, c_idx):
        m, l, gold, lsum, best_v, best_i = carry
        c0 = c_idx * chunk
        logits, valid = _chunk_logits(x, wp, bp, c0, chunk, local_rows,
                                      col_offset, vocab_size,
                                      w_vocab_axis)
        # Online logsumexp (the flash recurrence over vocab columns).
        cmax = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, cmax)
        l = l * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(logits - new_m[..., None]), axis=-1)
        # Gold logit: at most one (rank, chunk) contains each target.
        # The local-row bound matters in the vocab-parallel form: a
        # target owned by the NEXT rank falls in [local_rows, chunk)
        # here — chunk padding, whose logit reads -inf.
        idx = targets - col_offset - c0
        hit = (idx >= 0) & (idx < chunk) & (c0 + idx < local_rows)
        g = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[..., None], axis=-1)[..., 0]
        gold = gold + jnp.where(hit, g, 0.0)
        # Smoothing needs sum(logits) over the REAL vocab only.
        if label_smoothing:
            lsum = lsum + jnp.sum(jnp.where(valid, logits, 0.0), axis=-1)
        # Running argmax: strict > keeps the first max, matching
        # jnp.argmax over the full row.
        cidx = (jnp.argmax(logits, axis=-1).astype(jnp.int32)
                + col_offset + c0)
        take = cmax > best_v
        best_v = jnp.where(take, cmax, best_v)
        best_i = jnp.where(take, cidx, best_i)
        return (new_m, l, gold, lsum, best_v, best_i), None

    init = (jnp.full(bshape, NEG_INF, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.full(bshape, -jnp.inf, jnp.float32),
            jnp.full(bshape, -1, jnp.int32))
    (m, l, gold, lsum, best_v, best_i), _ = jax.lax.scan(
        body, init, jnp.arange(n_chunks))
    return m, l, gold, lsum, best_v, best_i


def _finish(lse, gold, lsum, best_i, targets, mask, vocab_size,
            label_smoothing):
    """(ce_sum, correct, mask_sum) from finished global stats."""
    if label_smoothing:
        gold = ((1.0 - label_smoothing) * gold
                + (label_smoothing / vocab_size) * lsum)
    fmask = mask.astype(jnp.float32)
    ce_sum = jnp.sum((lse - gold) * fmask)
    correct = jnp.sum(
        (best_i == targets.astype(jnp.int32)).astype(jnp.float32) * fmask)
    return ce_sum, correct, jnp.sum(fmask)


def _bwd_scan(x, wp, bp, targets, lse, coef, n_chunks, chunk,
              local_rows, col_offset, vocab_size, label_smoothing,
              w_vocab_axis):
    """The backward chunk scan over this head (shard): recompute each
    chunk's logits against the GLOBAL lse, form its softmax-minus-
    smoothed-onehot slice scaled by ``coef`` (mask * upstream), and
    accumulate (dx_local, dw_chunks, db_chunks). In the vocab-parallel
    form dx_local is this rank's partial (psum outside)."""
    targets = targets.astype(jnp.int32)
    scale = coef[..., None]
    batch_axes = tuple(range(x.ndim - 1))

    def body(dx, c_idx):
        c0 = c_idx * chunk
        logits, valid = _chunk_logits(x, wp, bp, c0, chunk, local_rows,
                                      col_offset, vocab_size,
                                      w_vocab_axis)
        p = jnp.exp(logits - lse[..., None])  # -inf columns -> exactly 0
        idx = targets - col_offset - c0
        hit = ((idx >= 0) & (idx < chunk)
               & (c0 + idx < local_rows))[..., None]
        onehot = hit & (jnp.arange(chunk) == jnp.clip(idx, 0, chunk - 1)
                        [..., None])
        dlogits = p - (1.0 - label_smoothing) * onehot
        if label_smoothing:
            dlogits = dlogits - (label_smoothing / vocab_size) * valid
        dlogits = (dlogits * scale).astype(x.dtype)
        wc = jax.lax.dynamic_slice_in_dim(
            wp, c0, chunk, axis=w_vocab_axis).astype(x.dtype)
        if w_vocab_axis == 0:
            dx = dx + jnp.einsum("...c,cd->...d", dlogits, wc,
                                 preferred_element_type=jnp.float32)
            dwc = jnp.einsum("...c,...d->cd", dlogits, x,
                             preferred_element_type=jnp.float32)
        else:
            dx = dx + jnp.einsum("...c,dc->...d", dlogits, wc,
                                 preferred_element_type=jnp.float32)
            dwc = jnp.einsum("...d,...c->dc", x, dlogits,
                             preferred_element_type=jnp.float32)
        dbc = jnp.sum(dlogits.astype(jnp.float32), axis=batch_axes)
        return dx, (dwc, dbc)

    dx0 = jnp.zeros(x.shape, jnp.float32)
    return jax.lax.scan(body, dx0, jnp.arange(n_chunks))


def _reassemble_dw(dw_chunks, db_chunks, rows, padded_rows, D,
                   w_vocab_axis, w_dtype, bias):
    """Stacked per-chunk head grads -> [rows]-sliced dw (+ db)."""
    if w_vocab_axis == 0:
        dw = dw_chunks.reshape(padded_rows, -1)[:rows]
    else:
        dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(
            D, padded_rows)[:, :rows]
    db = (db_chunks.reshape(padded_rows)[:rows].astype(
        bias.dtype if bias is not None else jnp.float32)
        if bias is not None else None)
    return dw.astype(w_dtype), db


# -------------------------------------------------- single-rank op

@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def fused_ce_sums(x: jax.Array, w: jax.Array, bias: Optional[jax.Array],
                  targets: jax.Array, mask: jax.Array,
                  vocab_size: int, chunk: int,
                  label_smoothing: float = 0.0,
                  w_vocab_axis: int = 0):
    """Unnormalized masked-CE pieces of ``x @ w (+ bias)`` without
    materializing the logits: (ce_sum, correct_sum, mask_sum) — the same
    contract as ops.losses.masked_ce_sums, so the pipeline-style global
    normalization applies unchanged.

    x: [..., D] features (compute dtype); w: head matrix with the vocab
    dim on ``w_vocab_axis`` (0: a [V, D] tied embedding table, 1: a
    [D, V] untied head kernel); targets/mask: [...]; ``chunk``: vocab
    columns per scan step (the peak-logits knob). Only ce_sum is
    differentiable (wrt x, w, bias); correct/mask_sum are metrics.
    """
    out, _ = _fwd_pass(x, w, bias, targets, mask, vocab_size, chunk,
                       label_smoothing, w_vocab_axis)
    return out


def _fwd_pass(x, w, bias, targets, mask, vocab_size, chunk,
              label_smoothing, w_vocab_axis):
    wp, bp, vpad = _pad_vocab(w, bias, vocab_size, chunk, w_vocab_axis)
    m, l, gold, lsum, _, best_i = _scan_stats(
        x, wp, bp, targets, vpad // chunk, chunk, vocab_size, 0,
        vocab_size, label_smoothing, w_vocab_axis)
    lse = m + jnp.log(l)
    out = _finish(lse, gold, lsum, best_i, targets, mask, vocab_size,
                  label_smoothing)
    return out, (x, w, bias, targets, mask, lse)


def _bwd_pass(vocab_size, chunk, label_smoothing, w_vocab_axis, res, g):
    x, w, bias, targets, mask, lse = res
    g_ce = g[0]  # correct/mask_sum are metrics: cotangents ignored
    wp, bp, vpad = _pad_vocab(w, bias, vocab_size, chunk, w_vocab_axis)
    coef = mask.astype(jnp.float32) * g_ce
    dx, (dw_chunks, db_chunks) = _bwd_scan(
        x, wp, bp, targets, lse, coef, vpad // chunk, chunk, vocab_size,
        0, vocab_size, label_smoothing, w_vocab_axis)
    dw, db = _reassemble_dw(dw_chunks, db_chunks, vocab_size, vpad,
                            x.shape[-1], w_vocab_axis, w.dtype, bias)
    return (dx.astype(x.dtype), dw, db,
            np.zeros(targets.shape, jax.dtypes.float0),
            _zeros_cotangent(mask))


fused_ce_sums.defvjp(_fwd_pass, _bwd_pass)


# ----------------------------------------------- vocab-parallel op

@partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _shard_ce_given_lse(x, w, bias, targets, mask, lse, off,
                        vocab_size, chunk, label_smoothing,
                        w_vocab_axis):
    """This shard's CE contribution GIVEN the global logsumexp.

    Value: -sum_t mask_t * smoothed_gold_shard(t) — the shard-
    decomposable part of masked CE (the caller adds sum(mask * lse)
    once and psums these over the model axis). Backward: the EXACT
    gradient of the GLOBAL masked CE restricted to this shard's
    columns — d ce/d logits = mask * (softmax - smoothed_onehot) is a
    total derivative through the lse path, so a stop-gradient lse
    VALUE is all it needs.

    Deliberately pure-local: no collectives inside the custom-VJP
    boundary. shard_map splits a replicated output's cotangent across
    devices expecting the body's OWN collectives' transposes to
    restore it — a convention hand-written backwards must not depend
    on. Here every collective (the lse combine, the psum of these
    values, the dx reassembly) lives in plain differentiable code
    whose AD is exact.
    """
    rows = w.shape[w_vocab_axis]
    wp, bp, rpad = _pad_vocab(w, bias, rows, chunk, w_vocab_axis)
    _, _, gold, lsum, _, _ = _scan_stats(
        x, wp, bp, targets, rpad // chunk, chunk, rows, off,
        vocab_size, label_smoothing, w_vocab_axis)
    if label_smoothing:
        gold = ((1.0 - label_smoothing) * gold
                + (label_smoothing / vocab_size) * lsum)
    return -jnp.sum(gold * mask.astype(jnp.float32))


def _shard_ce_fwd(x, w, bias, targets, mask, lse, off, vocab_size,
                  chunk, label_smoothing, w_vocab_axis):
    out = _shard_ce_given_lse(x, w, bias, targets, mask, lse, off,
                              vocab_size, chunk, label_smoothing,
                              w_vocab_axis)
    return out, (x, w, bias, targets, mask, lse, off)


def _shard_ce_bwd(vocab_size, chunk, label_smoothing, w_vocab_axis,
                  res, g_ce):
    x, w, bias, targets, mask, lse, off = res
    rows = w.shape[w_vocab_axis]
    wp, bp, rpad = _pad_vocab(w, bias, rows, chunk, w_vocab_axis)
    coef = mask.astype(jnp.float32) * g_ce
    dx, (dw_chunks, db_chunks) = _bwd_scan(
        x, wp, bp, targets, lse, coef, rpad // chunk, chunk, rows, off,
        vocab_size, label_smoothing, w_vocab_axis)
    dw, db = _reassemble_dw(dw_chunks, db_chunks, rows, rpad,
                            x.shape[-1], w_vocab_axis, w.dtype, bias)
    # dx is this shard's columns' contribution; x arrives replicated
    # over the model axis, so shard_map's input transpose psums the
    # rank contributions — exactly the reassembly the math wants.
    return (dx.astype(x.dtype), dw, db,
            np.zeros(targets.shape, jax.dtypes.float0),
            _zeros_cotangent(mask), jnp.zeros_like(lse),
            np.zeros(np.shape(off), jax.dtypes.float0))


_shard_ce_given_lse.defvjp(_shard_ce_fwd, _shard_ce_bwd)


def vocab_parallel_ce_sums(x, w, bias, targets, mask, vocab_size,
                           chunk, label_smoothing, w_vocab_axis,
                           model_axis):
    """The Megatron vocab-parallel fused CE — call INSIDE a shard_map
    where ``model_axis`` is manual and ``w``/``bias`` are this rank's
    vocab shard (every rank the same row count; rank r owns global ids
    [r*rows, (r+1)*rows)). Returns (ce_sum, correct, mask_sum) over
    the tokens this rank holds, replicated across the model axis
    (callers psum over the token axes)."""
    rows = w.shape[w_vocab_axis]
    off = jax.lax.axis_index(model_axis) * rows
    sg = jax.lax.stop_gradient
    # Global softmax stats from partial scans, in PLAIN code (see
    # _shard_ce_given_lse for why): stop-gradient inputs so AD never
    # tries to save this scan's chunk intermediates.
    wp, bp, rpad = _pad_vocab(sg(w), sg(bias), rows, chunk,
                              w_vocab_axis)
    m, l, _, _, best_v, best_i = _scan_stats(
        sg(x), wp, bp, targets, rpad // chunk, chunk, rows, off,
        vocab_size, 0.0, w_vocab_axis)
    M = jax.lax.pmax(m, model_axis)
    lse = M + jnp.log(jax.lax.psum(l * jnp.exp(m - M), model_axis))
    # First-max argmax across ranks: highest value wins; ties go to
    # the SMALLEST global id (the dense argmax convention). Ranks
    # that saw nothing hold -inf/-1 and lose the pmax.
    bv_glob = jax.lax.pmax(best_v, model_axis)
    cand = jnp.where((best_v == bv_glob) & (best_i >= 0), best_i,
                     INT_BIG)
    best_i = jax.lax.pmin(cand, model_axis)

    fmask = mask.astype(jnp.float32)
    ce_sum = (jax.lax.psum(
        _shard_ce_given_lse(x, w, bias, targets, mask, lse, off,
                            vocab_size, chunk, label_smoothing,
                            w_vocab_axis), model_axis)
        + jnp.sum(lse * fmask))
    correct = jnp.sum(
        (best_i == targets.astype(jnp.int32)).astype(jnp.float32)
        * fmask)
    return ce_sum, correct, jnp.sum(fmask)


# ------------------------------------------------------- dispatcher

def fused_masked_cross_entropy(x: jax.Array, w: jax.Array,
                               bias: Optional[jax.Array],
                               targets: jax.Array, mask: jax.Array, *,
                               vocab_size: int, chunk: int,
                               label_smoothing: float = 0.0,
                               w_vocab_axis: int = 0,
                               impl: str = "scan", mesh=None):
    """Mean masked CE + accuracy from the fused pieces — the drop-in
    for masked_softmax_cross_entropy + masked_accuracy when the caller
    holds features instead of logits. Returns (loss, accuracy).

    ``impl``: "scan" (this module's lax.scan formulation — all shapes,
    SPMD-transparent, and at mesh.model > 1 the vocab-parallel form
    with the head sharded over the model axis) or "kernel" (the Pallas
    flash-CE triple, ops/fused_ce_kernel.py — logits blocks live only
    in VMEM; single model rank only). Neither kernel nor scan needs a
    wrap at mesh.model == 1 — XLA partitions the scan transparently;
    the vocab-parallel and kernel paths run inside a shard_map (the
    Mosaic kernel because it has no GSPMD rule, the vocab-parallel
    form because its pmax/psum combine is written against manual
    axes)."""
    if impl == "kernel":
        ce_sum, correct, n = _kernel_sums(
            x, w, bias, targets, mask, vocab_size, label_smoothing,
            w_vocab_axis, mesh)
    elif impl == "scan":
        from tensorflow_distributed_tpu.parallel.mesh import AXIS_MODEL
        if mesh is not None and mesh.shape[AXIS_MODEL] > 1:
            ce_sum, correct, n = _tp_dispatch(
                x, w, bias, targets, mask, vocab_size, chunk,
                label_smoothing, w_vocab_axis, mesh)
        else:
            ce_sum, correct, n = fused_ce_sums(
                x, w, bias, targets, mask, vocab_size, chunk,
                label_smoothing, w_vocab_axis)
    else:
        raise ValueError(f"impl {impl!r}; have ('scan', 'kernel')")
    n = jnp.maximum(n, 1.0)
    return ce_sum / n, correct / n


def _tp_dispatch(x, w, bias, targets, mask, vocab_size, chunk,
                 label_smoothing, w_vocab_axis, mesh):
    """shard_map wrap for the vocab-parallel form: head rows split
    over ``model``, tokens over (data, seq), loss pieces psummed to
    replicated scalars."""
    from jax.sharding import PartitionSpec as P

    from tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_DATA, AXIS_MODEL, AXIS_SEQ)

    mp = mesh.shape[AXIS_MODEL]
    # Pad the head so every rank holds the same row count. Rows past
    # the real vocab are masked inside the op; their grads are zero
    # and sliced off here.
    w_full, b_full, vp = _pad_vocab(w, bias, vocab_size, mp,
                                    w_vocab_axis)
    if b_full is None:
        # Zero bias: None can't carry a partition spec; its grad lands
        # on this temporary and is discarded.
        b_full = jnp.zeros((vp,), jnp.float32)

    w_spec = (P(AXIS_MODEL, None) if w_vocab_axis == 0
              else P(None, AXIS_MODEL))
    tok = P(AXIS_DATA, AXIS_SEQ)

    def local(x, w, bias, targets, mask):
        ce, corr, n = vocab_parallel_ce_sums(
            x, w, bias, targets, mask, vocab_size, chunk,
            label_smoothing, w_vocab_axis, AXIS_MODEL)
        # Tokens shard over (data, seq); model ranks end replicated
        # (the op's combine), other axes hold replicas.
        return tuple(jax.lax.psum(v, (AXIS_DATA, AXIS_SEQ))
                     for v in (ce, corr, n))

    out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(AXIS_DATA, AXIS_SEQ, None), w_spec, P(AXIS_MODEL),
                  tok, tok),
        out_specs=(P(), P(), P()), check_vma=False)(
        x, w_full, b_full, targets, mask)
    return out


def _kernel_sums(x, w, bias, targets, mask, vocab_size, label_smoothing,
                 w_vocab_axis, mesh):
    from jax.sharding import PartitionSpec as P

    from tensorflow_distributed_tpu.ops.fused_ce_kernel import (
        fused_ce_sums_kernel, kernel_supported)
    from tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_DATA, AXIS_SEQ)

    D = x.shape[-1]
    if bias is None:
        # Materialize the zero bias OUTSIDE the shard_map: None is an
        # empty pytree and cannot carry a partition spec.
        bias = jnp.zeros((vocab_size,), jnp.float32)

    def local(x, w, bias, targets, mask):
        T = x.size // D
        if not kernel_supported(T, D):
            raise ValueError(
                f"ce_impl='kernel' unsupported for per-device shard "
                f"T={T}, D={D} (tokens must divide the 256 block, D "
                f"must be an 8 multiple); use ce_impl='scan'")
        return fused_ce_sums_kernel(
            x, w, bias, targets, mask, vocab_size,
            label_smoothing=label_smoothing, w_vocab_axis=w_vocab_axis)

    if mesh is None or all(
            mesh.shape[a] == 1 for a in (AXIS_DATA, AXIS_SEQ)):
        return local(x, w, bias, targets, mask)

    def sharded(x, w, bias, targets, mask):
        ce, corr, n = local(x, w, bias, targets, mask)
        # Tokens shard over (data, seq); every other axis holds
        # replicas (model == 1 is enforced upstream) — psum only the
        # token-sharding axes so replicas don't double-count.
        return tuple(jax.lax.psum(v, (AXIS_DATA, AXIS_SEQ))
                     for v in (ce, corr, n))

    tok = P(AXIS_DATA, AXIS_SEQ)
    return jax.shard_map(
        sharded, mesh=mesh,
        in_specs=(P(AXIS_DATA, AXIS_SEQ, None), P(), P(), tok, tok),
        out_specs=(P(), P(), P()), check_vma=False)(
        x, w, bias, targets, mask)
