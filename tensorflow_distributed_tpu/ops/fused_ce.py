"""Fused (vocab-chunked) linear + softmax cross-entropy.

The dense LM loss path materializes the full ``[B, L, V]`` logits tensor
twice per step — once in the forward (the head matmul's output) and once
in the backward (``softmax - onehot``). At GPT-2-small shapes (batch 8,
seq 1024, vocab 50257) that is ~825 MB of bf16 per materialization, pure
HBM traffic the MXU waits on. No reference counterpart — the reference's
output layer is 10 classes (`mnist_python_m.py:196,205`), where none of
this matters; it exists for the LM families' 50k-row heads.

This op fuses the head matmul into the loss with an **online softmax
over vocabulary chunks** (the same running (m, l) recurrence the flash
attention kernels use over key blocks, ops/flash_attention.py): the
forward scans vocab chunks of the head matrix, keeping only the running
max / normalizer / gold-logit / argmax accumulators (all ``[B, L]``),
and the custom-VJP backward **recomputes** each chunk's logits to form
its slice of ``softmax - onehot`` on the fly. Peak logits memory drops
from ``[B, L, V]`` to ``[B, L, chunk]``; full logits are never written.

Chunking over *vocab* (not tokens) is the SPMD-friendly choice: the
batch/seq dims — the ones sharded over the ``data``/``seq`` mesh axes —
pass through untouched, so under pjit every device simply runs the same
chunk loop on its own activation shard; no resharding, no collectives
beyond the loss reductions that were already there.

Semantics match ``ops.losses.masked_ce_sums`` exactly (unnormalized
(ce_sum, correct, mask_sum) pieces, f32 statistics, label smoothing as
the (1-eps)/eps-uniform target mixture); parity — values and gradients
— is pinned in tests/test_fused_ce.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _pad_vocab(w: jax.Array, bias: Optional[jax.Array], vocab_size: int,
               chunk: int, w_vocab_axis: int):
    """Zero-pad the vocab dim up to a chunk multiple so every scan step
    slices a full, non-clamped chunk (dynamic_slice clamps out-of-range
    starts, which would silently alias the last rows)."""
    pad = (-vocab_size) % chunk
    if pad:
        widths = [(0, 0)] * w.ndim
        widths[w_vocab_axis] = (0, pad)
        w = jnp.pad(w, widths)
        if bias is not None:
            bias = jnp.pad(bias, (0, pad))
    return w, bias, vocab_size + pad


def _chunk_logits(x: jax.Array, w: jax.Array, bias: Optional[jax.Array],
                  c0: jax.Array, chunk: int, vocab_size: int,
                  w_vocab_axis: int) -> Tuple[jax.Array, jax.Array]:
    """Logits for vocab columns [c0, c0+chunk) in f32, with columns past
    the real vocab masked to -inf. Returns (logits [..., chunk],
    valid [chunk] bool)."""
    wc = jax.lax.dynamic_slice_in_dim(w, c0, chunk, axis=w_vocab_axis)
    wc = wc.astype(x.dtype)
    eq = "...d,cd->...c" if w_vocab_axis == 0 else "...d,dc->...c"
    logits = jnp.einsum(eq, x, wc,
                        preferred_element_type=jnp.float32)
    if bias is not None:
        bc = jax.lax.dynamic_slice_in_dim(bias, c0, chunk, axis=0)
        logits = logits + bc.astype(jnp.float32)
    valid = (c0 + jnp.arange(chunk)) < vocab_size
    logits = jnp.where(valid, logits, -jnp.inf)
    return logits, valid


@partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def fused_ce_sums(x: jax.Array, w: jax.Array, bias: Optional[jax.Array],
                  targets: jax.Array, mask: jax.Array,
                  vocab_size: int, chunk: int,
                  label_smoothing: float = 0.0,
                  w_vocab_axis: int = 0):
    """Unnormalized masked-CE pieces of ``x @ w (+ bias)`` without
    materializing the logits: (ce_sum, correct_sum, mask_sum) — the same
    contract as ops.losses.masked_ce_sums, so the pipeline-style global
    normalization applies unchanged.

    x: [..., D] features (compute dtype); w: head matrix with the vocab
    dim on ``w_vocab_axis`` (0: a [V, D] tied embedding table, 1: a
    [D, V] untied head kernel); targets/mask: [...]; ``chunk``: vocab
    columns per scan step (the peak-logits knob). Only ce_sum is
    differentiable (wrt x, w, bias); correct/mask_sum are metrics.
    """
    out, _ = _fwd_pass(x, w, bias, targets, mask, vocab_size, chunk,
                       label_smoothing, w_vocab_axis)
    return out


def _fwd_pass(x, w, bias, targets, mask, vocab_size, chunk,
              label_smoothing, w_vocab_axis):
    wp, bp, vpad = _pad_vocab(w, bias, vocab_size, chunk, w_vocab_axis)
    n_chunks = vpad // chunk
    bshape = targets.shape
    targets = targets.astype(jnp.int32)

    def body(carry, c_idx):
        m, l, gold, lsum, best_v, best_i = carry
        c0 = c_idx * chunk
        logits, valid = _chunk_logits(x, wp, bp, c0, chunk, vocab_size,
                                      w_vocab_axis)
        # Online logsumexp (the flash recurrence over vocab columns).
        cmax = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, cmax)
        l = l * jnp.exp(m - new_m) + jnp.sum(
            jnp.exp(logits - new_m[..., None]), axis=-1)
        # Gold logit: at most one chunk contains each target.
        idx = targets - c0
        hit = (idx >= 0) & (idx < chunk)
        g = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[..., None], axis=-1)[..., 0]
        gold = gold + jnp.where(hit, g, 0.0)
        # Smoothing needs sum(logits) over the REAL vocab only.
        if label_smoothing:
            lsum = lsum + jnp.sum(jnp.where(valid, logits, 0.0), axis=-1)
        # Running argmax: strict > keeps the first max, matching
        # jnp.argmax over the full row.
        cidx = jnp.argmax(logits, axis=-1).astype(jnp.int32) + c0
        take = cmax > best_v
        best_v = jnp.where(take, cmax, best_v)
        best_i = jnp.where(take, cidx, best_i)
        return (new_m, l, gold, lsum, best_v, best_i), None

    init = (jnp.full(bshape, -jnp.inf, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.zeros(bshape, jnp.float32),
            jnp.full(bshape, -jnp.inf, jnp.float32),
            jnp.full(bshape, -1, jnp.int32))
    (m, l, gold, lsum, _, best_i), _ = jax.lax.scan(
        body, init, jnp.arange(n_chunks))

    lse = m + jnp.log(l)
    if label_smoothing:
        gold = ((1.0 - label_smoothing) * gold
                + (label_smoothing / vocab_size) * lsum)
    fmask = mask.astype(jnp.float32)
    ce_sum = jnp.sum((lse - gold) * fmask)
    correct = jnp.sum((best_i == targets).astype(jnp.float32) * fmask)
    out = (ce_sum, correct, jnp.sum(fmask))
    return out, (x, w, bias, targets, mask, lse)


def _bwd_pass(vocab_size, chunk, label_smoothing, w_vocab_axis, res, g):
    x, w, bias, targets, mask, lse = res
    g_ce = g[0]  # correct/mask_sum are metrics: cotangents ignored
    wp, bp, vpad = _pad_vocab(w, bias, vocab_size, chunk, w_vocab_axis)
    n_chunks = vpad // chunk
    targets = targets.astype(jnp.int32)
    # d ce_sum / d logits = mask * (softmax - smoothed_onehot), where
    # smoothed_onehot = (1-eps)*onehot + (eps/V) on real columns.
    scale = (mask.astype(jnp.float32) * g_ce)[..., None]
    batch_axes = tuple(range(x.ndim - 1))

    def body(dx, c_idx):
        c0 = c_idx * chunk
        logits, valid = _chunk_logits(x, wp, bp, c0, chunk, vocab_size,
                                      w_vocab_axis)
        p = jnp.exp(logits - lse[..., None])  # -inf columns -> exactly 0
        idx = targets - c0
        hit = ((idx >= 0) & (idx < chunk))[..., None]
        onehot = hit & (jnp.arange(chunk) == jnp.clip(idx, 0, chunk - 1)
                        [..., None])
        dlogits = p - (1.0 - label_smoothing) * onehot
        if label_smoothing:
            dlogits = dlogits - (label_smoothing / vocab_size) * valid
        dlogits = (dlogits * scale).astype(x.dtype)
        wc = jax.lax.dynamic_slice_in_dim(
            wp, c0, chunk, axis=w_vocab_axis).astype(x.dtype)
        if w_vocab_axis == 0:
            dx = dx + jnp.einsum("...c,cd->...d", dlogits, wc,
                                 preferred_element_type=jnp.float32)
            dwc = jnp.einsum("...c,...d->cd", dlogits, x,
                             preferred_element_type=jnp.float32)
        else:
            dx = dx + jnp.einsum("...c,dc->...d", dlogits, wc,
                                 preferred_element_type=jnp.float32)
            dwc = jnp.einsum("...d,...c->dc", x, dlogits,
                             preferred_element_type=jnp.float32)
        dbc = jnp.sum(dlogits.astype(jnp.float32), axis=batch_axes)
        return dx, (dwc, dbc)

    dx0 = jnp.zeros(x.shape, jnp.float32)
    dx, (dw_chunks, db_chunks) = jax.lax.scan(
        body, dx0, jnp.arange(n_chunks))

    # Reassemble the stacked per-chunk head grads and drop the padding.
    if w_vocab_axis == 0:
        dw = dw_chunks.reshape(vpad, -1)[:vocab_size]
    else:
        dw = jnp.moveaxis(dw_chunks, 0, 1).reshape(
            x.shape[-1], vpad)[:, :vocab_size]
    db = (db_chunks.reshape(vpad)[:vocab_size].astype(
        bias.dtype if bias is not None else jnp.float32)
        if bias is not None else None)
    return (dx.astype(x.dtype), dw.astype(w.dtype), db,
            np.zeros(targets.shape, jax.dtypes.float0),
            jnp.zeros_like(mask))


fused_ce_sums.defvjp(_fwd_pass, _bwd_pass)


def fused_masked_cross_entropy(x: jax.Array, w: jax.Array,
                               bias: Optional[jax.Array],
                               targets: jax.Array, mask: jax.Array, *,
                               vocab_size: int, chunk: int,
                               label_smoothing: float = 0.0,
                               w_vocab_axis: int = 0,
                               impl: str = "scan", mesh=None):
    """Mean masked CE + accuracy from the fused pieces — the drop-in
    for masked_softmax_cross_entropy + masked_accuracy when the caller
    holds features instead of logits. Returns (loss, accuracy).

    ``impl``: "scan" (this module's lax.scan formulation — all shapes,
    SPMD-transparent) or "kernel" (the Pallas flash-CE triple,
    ops/fused_ce_kernel.py — logits blocks live only in VMEM). The
    kernel has no GSPMD partitioning rule, so on a multi-device
    ``mesh`` it runs inside a shard_map over the batch/seq axes with
    the loss reductions psummed — the same wrap the flash-attention
    dispatcher uses (ops/flash_attention.py::attention).
    """
    if impl == "kernel":
        ce_sum, correct, n = _kernel_sums(
            x, w, bias, targets, mask, vocab_size, label_smoothing,
            w_vocab_axis, mesh)
    elif impl == "scan":
        ce_sum, correct, n = fused_ce_sums(
            x, w, bias, targets, mask, vocab_size, chunk,
            label_smoothing, w_vocab_axis)
    else:
        raise ValueError(f"impl {impl!r}; have ('scan', 'kernel')")
    n = jnp.maximum(n, 1.0)
    return ce_sum / n, correct / n


def _kernel_sums(x, w, bias, targets, mask, vocab_size, label_smoothing,
                 w_vocab_axis, mesh):
    from jax.sharding import PartitionSpec as P

    from tensorflow_distributed_tpu.ops.fused_ce_kernel import (
        fused_ce_sums_kernel, kernel_supported)
    from tensorflow_distributed_tpu.parallel.mesh import (
        AXIS_DATA, AXIS_SEQ)

    D = x.shape[-1]
    if bias is None:
        # Materialize the zero bias OUTSIDE the shard_map: None is an
        # empty pytree and cannot carry a partition spec.
        bias = jnp.zeros((vocab_size,), jnp.float32)

    def local(x, w, bias, targets, mask):
        T = x.size // D
        if not kernel_supported(T, D):
            raise ValueError(
                f"ce_impl='kernel' unsupported for per-device shard "
                f"T={T}, D={D} (tokens must divide the 256 block, D "
                f"must be an 8 multiple); use ce_impl='scan'")
        return fused_ce_sums_kernel(
            x, w, bias, targets, mask, vocab_size,
            label_smoothing=label_smoothing, w_vocab_axis=w_vocab_axis)

    if mesh is None or all(
            mesh.shape[a] == 1 for a in (AXIS_DATA, AXIS_SEQ)):
        return local(x, w, bias, targets, mask)

    def sharded(x, w, bias, targets, mask):
        ce, corr, n = local(x, w, bias, targets, mask)
        # Tokens shard over (data, seq); every other axis holds
        # replicas (model == 1 is enforced upstream) — psum only the
        # token-sharding axes so replicas don't double-count.
        return tuple(jax.lax.psum(v, (AXIS_DATA, AXIS_SEQ))
                     for v in (ce, corr, n))

    tok = P(AXIS_DATA, AXIS_SEQ)
    return jax.shard_map(
        sharded, mesh=mesh,
        in_specs=(P(AXIS_DATA, AXIS_SEQ, None), P(), P(), tok, tok),
        out_specs=(P(), P(), P()), check_vma=False)(
        x, w, bias, targets, mask)
