"""Cross-run regression ledger over the committed bench artifacts.

Every perf PR in this repo gates on a committed artifact (GRADSYNC,
SERVEBENCH, SLOBENCH, FIREBENCH, ELASTICBENCH, PLANBENCH, CALIBBENCH,
...), but until now "did my change regress an OLD gate" meant eyeballing
JSON diffs. This module is the declarative alternative: a MANIFEST maps
each artifact to its headline metrics — where the number lives, which
direction is good, and how much noise a rerun is allowed — and the CLI
compares a fresh artifact (or the working tree's copy) against the
COMMITTED baseline (``git show <ref>:<name>``), printing a readable
table and exiting nonzero on any regression::

    # the working tree's artifacts vs HEAD (the t1 smoke — clean tree
    # must pass clean):
    python -m tensorflow_distributed_tpu.observe.regress

    # a freshly-regenerated artifact vs the committed one:
    python -m tensorflow_distributed_tpu.observe.regress \
        --artifact FIREBENCH.json --fresh /tmp/FIREBENCH.json

Check semantics (per fresh-vs-baseline pair):

- ``higher`` / ``lower``: the good direction; a move the BAD way
  beyond ``max(rtol*|baseline|, atol)`` is a REGRESSION, beyond it
  the GOOD way is reported IMPROVED, inside the band is OK. CPU
  timings carry generous rtols — the ledger flags real slides, not
  scheduler jitter.
- ``truthy``: a gate bool (or a must-be-nonzero count) that must stay
  truthy. A baseline that is ALREADY falsy skips the check (an
  expected-broken artifact — e.g. a TPU-probe snapshot recorded with
  rc!=0 — must not block unrelated PRs).
- ``equal``: exact (correctness counts like token_identical 32/32).

A metric missing from the fresh artifact while present in the baseline
is a regression (gates must not silently disappear); present only in
the fresh one is reported as new and passes. Artifacts not present in
the baseline ref are skipped with a note — the ledger audits committed
history, it doesn't invent it.

Stdlib-only (jax-free, fast): the manifest is data, the comparisons
are arithmetic, git is the only external dependency and only for
baseline loading (``--baseline`` sidesteps it).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass(frozen=True)
class Check:
    """One ledger entry: where the number lives and what "worse"
    means. ``path`` is dotted; in a JSONL artifact the FIRST component
    selects the line by its ``metric`` field, the rest walks into the
    record (``fire_goodput.value``). ``rtol`` is relative to the
    baseline, ``atol`` absolute — the noise band is their max."""

    path: str
    direction: str  # higher | lower | truthy | equal
    rtol: float = 0.0
    atol: float = 0.0


def _jsonl_checks(*specs) -> List[Check]:
    return [Check(*s) for s in specs]


#: artifact name -> (format, checks). Formats: "jsonl" (one JSON per
#: line, "metric"-discriminated), "json" (one document).
MANIFEST: Dict[str, Tuple[str, List[Check]]] = {
    "GRADSYNC.json": ("json", _jsonl_checks(
        ("checks.identity", "truthy"),
        ("checks.overlap_not_slower", "truthy"),
        ("identity.params", "truthy"),
        ("steps.overlap.min_ms", "lower", 0.5),
        ("steps.serial.min_ms", "lower", 0.5),
        ("exposed_comm_ms.overlap", "lower", 0.6),
        ("ok", "truthy"),
    )),
    "SERVEBENCH.json": ("jsonl", _jsonl_checks(
        ("serve_speedup.value", "higher", 0.5),
        ("serve_continuous_tokens_per_sec.value", "higher", 0.5),
        ("serve_spec_tokens_per_sec.value", "higher", 0.5),
        ("serve_spec_tokens_per_sec.accept_rate", "higher", 0.0, 0.05),
        ("serve_spec_speedup.value", "higher", 0.3),
        ("serve_int8_slots_at_budget.ratio", "higher", 0.0, 0.05),
        ("serve_int8_greedy_divergence.value", "lower", 0.0, 0.0),
        ("serve_slo_p95_ttft_high.ratio", "lower", 1.0),
        ("serve_checks.speedup_ok", "truthy"),
        ("serve_checks.token_identical", "equal"),
        ("serve_tp_cache_bytes_per_slot.ratio", "higher", 0.0, 0.05),
        ("serve_checks.tp_cache_ratio_ok", "truthy"),
        ("serve_checks.tp_token_identical", "equal"),
    )),
    "SLOBENCH.json": ("jsonl", _jsonl_checks(
        ("slo_control_alerts.value", "lower", 0.0, 0.0),
        ("slo_fire_alerts.value", "truthy"),
        ("slo_instrumentation_tokens_per_sec.ratio",
         "higher", 0.0, 0.1),
        ("slo_checks.control_quiet", "truthy"),
        ("slo_checks.fire_alerted", "truthy"),
        ("slo_checks.traces_balanced", "truthy"),
        ("slo_checks.recovery_instants_ok", "truthy"),
        ("slo_checks.trace_spans_restart", "truthy"),
    )),
    "TUNEBENCH.json": ("jsonl", _jsonl_checks(
        ("tune_goodput.ratio", "higher", 0.1),
        ("tune_control.tune_actions", "lower", 0.0, 0.0),
        ("tune_autopilot_tokens_per_sec.ratio", "higher", 0.0, 0.1),
        ("tune_checks.converged", "truthy"),
        ("tune_checks.identity", "truthy"),
        ("tune_checks.quiet_control", "truthy"),
        ("tune_checks.spec_retuned", "truthy"),
        ("tune_checks.cli_wired", "truthy"),
        ("tune_checks.overhead_ok", "truthy"),
        ("tune_checks.evidence_ok", "truthy"),
    )),
    "FIREBENCH.json": ("jsonl", _jsonl_checks(
        ("fire_goodput.value", "higher", 0.15),
        ("fire_tokens_per_sec.value", "higher", 0.5),
        ("fire_checks.goodput_ok", "truthy"),
        ("fire_checks.lost_requests", "lower", 0.0, 0.0),
        ("fire_checks.token_identical", "equal"),
    )),
    "ELASTICBENCH.json": ("jsonl", _jsonl_checks(
        ("elastic_shrink_last_loss.delta_vs_baseline",
         "lower", 0.0, 1e-3),
        ("elastic_grow_last_loss.delta_vs_baseline",
         "lower", 0.0, 1e-3),
        ("elastic_shrink_reshard_seconds.value", "lower", 1.0),
        ("elastic_checks.shrink_loss_ok", "truthy"),
        ("elastic_checks.shrink_zero_lost_steps", "truthy"),
        ("elastic_checks.shrink_resharded_ok", "truthy"),
        ("elastic_checks.grow_loss_ok", "truthy"),
        ("elastic_checks.grow_zero_lost_steps", "truthy"),
        ("elastic_checks.grow_resharded_ok", "truthy"),
    )),
    "PLANBENCH.json": ("jsonl", _jsonl_checks(
        ("plan_checks.gpt.pick_ok", "truthy"),
        ("plan_checks.gpt.pick_vs_best", "lower", 0.0, 0.15),
        ("plan_checks.gpt.hbm_rank_ok", "truthy"),
        ("plan_checks.moe.pick_ok", "truthy"),
        ("plan_checks.moe.pick_vs_best", "lower", 0.0, 0.15),
        ("plan_checks.moe.hbm_rank_ok", "truthy"),
    )),
    "CALIBBENCH.json": ("jsonl", _jsonl_checks(
        ("calib_checks.calibrated_better", "truthy"),
        ("calib_checks.within_band", "truthy"),
        ("calib_checks.regress_flags_degraded", "truthy"),
        ("calib_checks.regress_clean_on_committed", "truthy"),
        ("calib_fit.calibrated_median_rel_err", "lower", 0.0, 0.25),
    )),
    "DETECTBENCH.json": ("jsonl", _jsonl_checks(
        # Recall/precision/bundle gates are exact (deterministic fault
        # plans are ground truth); the overhead ratio carries a
        # generous CPU band.
        ("detect_checks.recall_ok", "truthy"),
        ("detect_checks.precision_ok", "truthy"),
        ("detect_checks.bundle_ok", "truthy"),
        ("detect_checks.overhead_ok", "truthy"),
        ("detect_train_recall.flagged", "equal"),
        ("detect_serve_recall.flagged", "equal"),
        ("detect_train_precision.anomalies", "lower", 0.0, 0.0),
        ("detect_serve_precision.anomalies", "lower", 0.0, 0.0),
        ("detect_bundle.named_in_restart", "truthy"),
        ("detect_bundle.postmortem_cli_ok", "truthy"),
        ("detect_overhead.ratio", "higher", 0.0, 0.1),
    )),
    "PAGEBENCH.json": ("jsonl", _jsonl_checks(
        # Hit-rate / identity / lost are exact (seeded trace, greedy
        # determinism); the warm-TTFT ratio carries a generous CPU
        # band; FLOPs-saved and slots-at-budget are arithmetic over
        # engine counters — tight noise bands.
        ("page_checks.token_identical", "equal"),
        ("page_checks.dense_identical", "equal"),
        ("page_checks.lost", "lower", 0.0, 0.0),
        ("page_checks.flops_ok", "truthy"),
        ("page_checks.slots_ok", "truthy"),
        ("page_checks.ttft_ok", "truthy"),
        ("page_prefill_flops.saved_frac", "higher", 0.0, 0.05),
        ("page_hit.rate", "higher", 0.0, 0.1),
        ("page_hbm.slots_ratio", "higher", 0.0, 0.1),
        ("page_warm_ttft.ratio", "lower", 0.5),
    )),
    "FLEETBENCH.json": ("jsonl", _jsonl_checks(
        # Correctness gates are exact (token identity, zero lost/
        # shed, control quiet, drills fired); goodput carries a
        # generous CPU band and the recovery p99 is bounded by its
        # own gate bool rather than a noisy ms compare.
        ("fleet_checks.identity_token_identical", "equal"),
        ("fleet_checks.identity_lost", "lower", 0.0, 0.0),
        ("fleet_checks.identity_drills_ok", "truthy"),
        ("fleet_checks.goodput_ok", "truthy"),
        ("fleet_checks.loop_lost", "lower", 0.0, 0.0),
        ("fleet_checks.loop_shed", "lower", 0.0, 0.0),
        ("fleet_checks.control_quiet_ok", "truthy"),
        ("fleet_checks.recovery_p99_ok", "truthy"),
        ("fleet_checks.staleness_ok", "truthy"),
        ("fleet_checks.swaps_ok", "truthy"),
        ("fleet_checks.fault_drills_ok", "truthy"),
        ("fleet_goodput.value", "higher", 0.15),
        ("fleet_fault_staleness.rolling_swaps", "equal"),
    )),
    "FLEETOBSBENCH.json": ("jsonl", _jsonl_checks(
        # Observatory gates are bools the bench itself derives
        # (stitched-trace balance across a real SIGKILL failover,
        # alert-on-fault/quiet-on-control, decomposition residual,
        # snapshot==report parity, fleetview render); the only analog
        # metric is the tracing-overhead throughput ratio, banded
        # generously for CPU noise on top of its own >= gate.
        ("fleetobs_checks.control_quiet", "truthy"),
        ("fleetobs_checks.fault_alerted", "truthy"),
        ("fleetobs_checks.lost", "lower", 0.0, 0.0),
        ("fleetobs_checks.traces_balanced", "truthy"),
        ("fleetobs_checks.failover_legs_ok", "truthy"),
        ("fleetobs_checks.decomp_ok", "truthy"),
        ("fleetobs_checks.snapshot_agrees_with_report", "truthy"),
        ("fleetobs_checks.fleetview_ok", "truthy"),
        ("fleetobs_checks.overhead_ok", "truthy"),
        ("fleetobs_overhead.ratio", "higher", 0.1),
    )),
    "GENBENCH.json": ("jsonl", _jsonl_checks(
        ("gen_prefill_tokens_per_sec.value", "higher", 0.3),
        ("gen_decode_tokens_per_sec.value", "higher", 0.3),
        ("gen_decode_tokens_per_sec_gqa.value", "higher", 0.3),
    )),
    "MOEBENCH.json": ("jsonl", _jsonl_checks(
        ("moe_train_tokens_per_sec.value", "higher", 0.3),
        ("moe_train_active_mfu.value", "higher", 0.3),
    )),
    "RINGBENCH.json": ("jsonl", _jsonl_checks(
        ("ring_block_flash_vs_einsum_fwd_speedup.value",
         "higher", 0.3),
    )),
}

#: name-prefix fallbacks (the numbered driver snapshots: BENCH_r01..):
#: rc must not turn nonzero. (kept minimal — their "tail" blob is a
#: log, not a metrics schema).
PREFIX_MANIFEST: List[Tuple[str, Tuple[str, List[Check]]]] = [
    ("BENCH_r", ("json", _jsonl_checks(("rc", "lower", 0.0, 0.0)))),
]


def manifest_for(name: str) -> Optional[Tuple[str, List[Check]]]:
    if name in MANIFEST:
        return MANIFEST[name]
    for prefix, spec in PREFIX_MANIFEST:
        if name.startswith(prefix):
            return spec
    return None


def manifest_names() -> List[str]:
    """Every artifact the ledger covers that exists in the working
    tree (exact names plus prefix matches)."""
    names = [n for n in MANIFEST
             if os.path.exists(os.path.join(REPO_ROOT, n))]
    for prefix, _ in PREFIX_MANIFEST:
        for fn in sorted(os.listdir(REPO_ROOT)):
            if fn.startswith(prefix) and fn.endswith(".json"):
                names.append(fn)
    return sorted(set(names))


# --- artifact loading --------------------------------------------------

def parse_artifact(text: str, fmt: str) -> Dict[str, Any]:
    """Normalize to one navigable dict: JSON documents pass through;
    JSONL becomes ``{metric: record}`` (last line per metric wins —
    reruns replace)."""
    if fmt == "json":
        return json.loads(text)
    out: Dict[str, Any] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and rec.get("metric"):
            out[str(rec["metric"])] = rec
    return out


_MISSING = object()


def resolve(doc: Any, path: str) -> Any:
    """Walk a dotted path; the sentinel ``_MISSING`` (is-checked by
    callers) when any component is absent."""
    cur = doc
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return _MISSING
    return cur


def baseline_text(name: str, ref: str = "HEAD") -> Optional[str]:
    """The committed artifact's bytes at ``ref`` (None when untracked
    there, or git is unavailable)."""
    try:
        out = subprocess.run(["git", "show", f"{ref}:{name}"],
                             capture_output=True, text=True,
                             timeout=30, cwd=REPO_ROOT)
    except Exception:
        return None
    return out.stdout if out.returncode == 0 else None


# --- comparison --------------------------------------------------------

def compare_check(check: Check, base_doc: Dict[str, Any],
                  fresh_doc: Dict[str, Any]) -> Dict[str, Any]:
    """One check's finding: verdict in ok | improved | regression |
    skip (+ baseline/fresh/why)."""
    base = resolve(base_doc, check.path)
    fresh = resolve(fresh_doc, check.path)
    out: Dict[str, Any] = {"check": check.path,
                           "direction": check.direction,
                           "baseline": None if base is _MISSING else base,
                           "fresh": None if fresh is _MISSING else fresh}
    if base is _MISSING:
        out.update(verdict="skip", why="not in baseline (new metric)")
        return out
    if fresh is _MISSING:
        out.update(verdict="regression",
                   why="metric disappeared from the fresh artifact")
        return out
    if check.direction == "truthy":
        if not base:
            out.update(verdict="skip", why="baseline already failing")
        elif not fresh:
            out.update(verdict="regression", why="gate went falsy")
        else:
            out.update(verdict="ok")
        return out
    if check.direction == "equal":
        out.update(verdict="ok" if fresh == base else "regression",
                   why=None if fresh == base else "exact gate changed")
        return out
    if not isinstance(base, (int, float)) or not isinstance(
            fresh, (int, float)) or isinstance(base, bool) \
            or isinstance(fresh, bool):
        out.update(verdict="skip", why="non-numeric value")
        return out
    band = max(check.rtol * abs(float(base)), check.atol)
    delta = float(fresh) - float(base)
    worse = delta > band if check.direction == "lower" \
        else -delta > band
    better = -delta > band if check.direction == "lower" \
        else delta > band
    out["band"] = round(band, 6)
    if worse:
        out.update(verdict="regression",
                   why=f"moved {delta:+.6g} ({check.direction} is "
                       f"better; band ±{band:.6g})")
    elif better:
        out.update(verdict="improved")
    else:
        out.update(verdict="ok")
    return out


def compare_artifact(name: str, fresh_path: Optional[str] = None,
                     baseline_path: Optional[str] = None,
                     ref: str = "HEAD") -> List[Dict[str, Any]]:
    """Every manifest finding for one artifact. ``fresh_path``
    defaults to the working-tree copy, the baseline to
    ``git show <ref>:<name>`` (``baseline_path`` overrides for
    git-free use)."""
    spec = manifest_for(name)
    if spec is None:
        return [{"artifact": name, "verdict": "skip",
                 "why": "no manifest entry"}]
    fmt, checks = spec
    fresh_path = fresh_path or os.path.join(REPO_ROOT, name)
    if not os.path.exists(fresh_path):
        return [{"artifact": name, "verdict": "regression",
                 "why": f"fresh artifact missing: {fresh_path}"}]
    with open(fresh_path) as f:
        fresh_doc = parse_artifact(f.read(), fmt)
    if baseline_path is not None:
        with open(baseline_path) as f:
            base_text: Optional[str] = f.read()
    else:
        base_text = baseline_text(name, ref)
    if base_text is None:
        return [{"artifact": name, "verdict": "skip",
                 "why": f"not committed at {ref}"}]
    base_doc = parse_artifact(base_text, fmt)
    findings = []
    for check in checks:
        finding = compare_check(check, base_doc, fresh_doc)
        finding["artifact"] = name
        findings.append(finding)
    return findings


def render_table(findings: Sequence[Dict[str, Any]]) -> str:
    def fmt_val(v):
        if isinstance(v, float):
            return f"{v:.6g}"
        s = str(v)
        return s if len(s) <= 18 else s[:15] + "..."

    lines = [f"{'artifact':<18} {'check':<44} {'baseline':>12} "
             f"{'fresh':>12} verdict"]
    for f in findings:
        mark = {"ok": "ok", "improved": "OK+", "skip": "--",
                "regression": "REGRESSION"}[f["verdict"]]
        lines.append(
            f"{f.get('artifact', '?'):<18} {f.get('check', '-'):<44} "
            f"{fmt_val(f.get('baseline', '-')):>12} "
            f"{fmt_val(f.get('fresh', '-')):>12} {mark}")
        if f.get("why") and f["verdict"] != "ok":
            lines.append(f"{'':<18}   ^ {f['why']}")
    n_reg = sum(1 for f in findings if f["verdict"] == "regression")
    n_imp = sum(1 for f in findings if f["verdict"] == "improved")
    lines.append(f"regress: {len(findings)} checks, {n_reg} "
                 f"regression(s), {n_imp} improvement(s)")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.observe.regress",
        description="compare bench artifacts against the committed "
                    "baseline; exit 1 on any regression")
    parser.add_argument("--artifact", action="append", default=[],
                        help="artifact name(s) to check (default: "
                        "every manifest artifact present in the "
                        "working tree)")
    parser.add_argument("--fresh", default="",
                        help="path of a freshly-generated artifact "
                        "(requires exactly one --artifact; default: "
                        "the working-tree copy)")
    parser.add_argument("--baseline", default="",
                        help="explicit baseline file (default: git "
                        "show <ref>:<name>)")
    parser.add_argument("--ref", default="HEAD",
                        help="git ref the baseline is read from")
    parser.add_argument("--list", action="store_true",
                        help="print the manifest and exit")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable findings")
    args = parser.parse_args(argv)
    if args.list:
        for name in sorted(MANIFEST):
            fmt, checks = MANIFEST[name]
            print(f"{name} ({fmt})")
            for c in checks:
                band = (f" rtol={c.rtol}" if c.rtol else "") + (
                    f" atol={c.atol}" if c.atol else "")
                print(f"  {c.path:<46} {c.direction}{band}")
        return 0
    names = args.artifact or manifest_names()
    if args.fresh and len(names) != 1:
        parser.error("--fresh needs exactly one --artifact")
    findings: List[Dict[str, Any]] = []
    for name in names:
        findings.extend(compare_artifact(
            name, fresh_path=args.fresh or None,
            baseline_path=args.baseline or None, ref=args.ref))
    print(json.dumps(findings, default=str) if args.json
          else render_table(findings))
    bad = [f for f in findings if f["verdict"] == "regression"]
    if bad:
        print(f"regress: FAILED — {len(bad)} regression(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
