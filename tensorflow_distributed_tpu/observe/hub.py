"""The Observatory: one object wiring the observe/ instruments into a run.

The training loop (train/loop.py) drives it at four well-defined
points per step — data fetch, async dispatch, blocking on the oldest
in-flight step, cadence host work — and at the phase boundaries (eval,
checkpoint, restore, preemption drain). Everything else (registry
fan-out, Chrome-trace spans, rolling step-time stats, throughput/MFU
windows, goodput ledger) happens here so the loop body stays thin.

Fully inert when no sink, trace path, or CSV is configured: every
method returns a null context or no-ops, so the loop calls them
unconditionally.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Any, Dict, Iterator, Optional

from tensorflow_distributed_tpu.observe import device as device_mod
from tensorflow_distributed_tpu.observe import goodput as goodput_mod
from tensorflow_distributed_tpu.observe import mfu as mfu_mod
from tensorflow_distributed_tpu.observe.anomaly import AnomalyHub
from tensorflow_distributed_tpu.observe.flightrec import (
    FlightRecorder, FlightRecorderSink)
from tensorflow_distributed_tpu.observe.goodput import GoodputCounter
from tensorflow_distributed_tpu.observe import registry as registry_mod
from tensorflow_distributed_tpu.observe.registry import (
    CsvSink, JsonlSink, MetricsRegistry, host_tags)
from tensorflow_distributed_tpu.observe.steptime import StepTimeBreakdown
from tensorflow_distributed_tpu.observe.trace import ChromeTracer


def _build_flightrec(ocfg, tags: Optional[Dict[str, Any]],
                     run_config: Any = None) -> FlightRecorder:
    """The crash flight recorder both observatories arm the same way:
    bundle-dir ring + snapshot cadence from the config, provenance
    (git sha, calibration id, host tags, the run config) in the
    bundle meta, signal hooks installed."""
    meta: Dict[str, Any] = {
        **registry_mod.artifact_stamp(
            registry_mod.default_calibration_path()),
        **(tags or {}),
    }
    if run_config is not None:
        import dataclasses

        meta["config"] = (dataclasses.asdict(run_config)
                          if dataclasses.is_dataclass(run_config)
                          and not isinstance(run_config, type)
                          else run_config)
    rec = FlightRecorder(ocfg.flightrec, ring=ocfg.flightrec_ring,
                         snapshot_every=ocfg.flightrec_snapshot_every,
                         meta=meta)
    rec.install()
    return rec


def _crash_dump(flightrec: Optional[FlightRecorder],
                registry: MetricsRegistry) -> None:
    """Called from the observatories' close(): when an exception is in
    flight (non-finite halt, recovery-budget exhaustion, stall — every
    fatal path funnels through the run's ``finally: obs.close()``),
    dump the postmortem bundle and leave one ``postmortem`` record in
    the JSONL (flushed per record, so it survives)."""
    if flightrec is None or flightrec.dumped is not None:
        return
    exc = sys.exc_info()[1]
    if exc is None:
        return
    reason = f"{type(exc).__name__}: {exc}"
    path = flightrec.dump(reason=reason)
    if path:
        registry.emit("postmortem", bundle=path, reason=reason)


def _emit_device_time(registry: MetricsRegistry, profile_dir: str,
                      calibration: str = "") -> list:
    """Parse the profiler capture under ``profile_dir``
    (observe/xprof.py), join each attributed program with the roofline
    prediction from its registered compile costs (at the calibration
    profile when one is given), and emit one ``device_time`` record
    per program through ``registry``. The measured-vs-predicted pair
    observe.report's "Device time" section renders. Never raises —
    xprof degrades to explicit-null records, and anything past that is
    swallowed (telemetry must not take down a finished run)."""
    try:
        from tensorflow_distributed_tpu.observe import xprof

        costs = {r["program"]: r for r in device_mod.programs()
                 if r.get("program")}
        recs = xprof.device_time_records(profile_dir,
                                         programs=list(costs))
        cal = None
        if calibration:
            try:
                from tensorflow_distributed_tpu.analysis.planner \
                    .calibrate import load_calibration
                cal = load_calibration(calibration)
            except Exception as e:
                # A mis-pointed profile must not die silently: the
                # run finishes, but the user is told the device-time
                # predictions fell back to the static tables.
                import sys

                print(f"observe: --plan-calibration {calibration}: "
                      f"{e} — device-time predictions use the static "
                      f"tables", file=sys.stderr)
        hw = None
        try:
            from tensorflow_distributed_tpu.analysis.planner.score \
                import detect_hardware
            hw = detect_hardware(calibration=cal)
        except Exception:
            pass  # no backend — measured-only records
        recs = xprof.with_predictions(recs, costs, hw)
        for rec in recs:
            registry.emit("device_time", **rec)
        return recs
    except Exception:
        return []


class ServeObservatory:
    """mode=serve's observability bundle: the metrics registry (JSONL
    sink, appended on a journal resume), the per-request
    :class:`~..serve_trace.ServeTracer` (resumed too — one trace file
    spans a supervised restart), the :class:`~..slo.SLOMonitor` built
    from ``--observe.slo``, and the rolling-snapshot export knobs —
    everything serve/run.py hands the scheduler and engine. Owns the
    process-level installs (active registry for library-level events,
    compiled-program registration) and tears them down in
    :meth:`close`, mirroring the training Observatory."""

    def __init__(self, ocfg, *, chief: bool = True,
                 tags: Optional[Dict[str, Any]] = None,
                 process_index: int = 0, resumed: bool = False,
                 run_config: Any = None):
        from tensorflow_distributed_tpu.observe.serve_trace import (
            ServeTracer)
        from tensorflow_distributed_tpu.observe.slo import (
            SLOMonitor, parse_slo, parse_windows)

        sinks = []
        if ocfg.metrics_jsonl:
            # A journal-resumed leg APPENDS: the dead leg's records
            # are part of the same serving story (the train-side
            # --resume convention).
            sinks.append(JsonlSink(ocfg.metrics_jsonl, append=resumed))
        self.flightrec = None
        if ocfg.flightrec:
            # Crash flight recorder (observe/flightrec.py): the ring
            # rides the registry as a sink; a SIGKILL'd leg leaves its
            # last fsync'd snapshot as the postmortem bundle.
            self.flightrec = _build_flightrec(ocfg, tags, run_config)
            sinks.append(FlightRecorderSink(self.flightrec))
        self.registry = MetricsRegistry(
            sinks, enabled=chief, tags=tags or {},
            max_records=ocfg.max_records,
            validate=bool(getattr(run_config, "check", False)))
        # Online anomaly detection on the decode-step clock
        # (observe/anomaly.py): the scheduler feeds TTFT / decode-wall
        # / queue-depth samples it already has on host; "anomaly"
        # records flow to the same sinks and the live incident state
        # rides metrics_snapshot() for the export-path pollers.
        self.anomalies = None
        if ocfg.anomaly:
            self.anomalies = AnomalyHub(emit=self.registry.emit,
                                        window=ocfg.anomaly_window,
                                        phase="serve")
        self.tracer = None
        if ocfg.trace:
            self.tracer = ServeTracer(ocfg.trace, enabled=chief,
                                      pid=process_index,
                                      resume=resumed,
                                      durable=getattr(
                                          ocfg, "trace_durable", False))
        self.slo_monitor = None
        self.status_every = 0
        fast, _slow = parse_windows(ocfg.slo_windows)
        if ocfg.slo:
            self.slo_monitor = SLOMonitor(
                parse_slo(ocfg.slo), fast_window=fast,
                slow_window=_slow, burn_threshold=ocfg.slo_burn,
                emit=self.registry.emit, tracer=self.tracer)
            # The live status line defaults to the fast window's
            # cadence when the monitor is armed.
            self.status_every = ocfg.slo_status_every or fast
        elif ocfg.slo_status_every:
            self.status_every = ocfg.slo_status_every
        self.export_every = ocfg.export_every
        self.export_path = ocfg.export_path
        # The online controller (observe/autopilot.py): tune records
        # flow to the same sinks; actuation happens scheduler-side
        # through the control-command path. The metrics JSONL this
        # bundle itself writes is the stream loop 1 tails for the
        # compile × device_time join.
        self.autopilot = None
        if getattr(ocfg, "autopilot", False):
            from tensorflow_distributed_tpu.observe.autopilot import (
                Autopilot)
            pins = tuple(
                p.strip() for p in ocfg.autopilot_pin.split(",")
                if p.strip())
            self.autopilot = Autopilot(
                emit=self.registry.emit,
                every=ocfg.autopilot_every,
                confirm=ocfg.autopilot_confirm,
                cooldown=ocfg.autopilot_cooldown,
                drift_tol=ocfg.autopilot_drift_tol,
                pins=pins,
                metrics_path=ocfg.metrics_jsonl,
                calibration_path=ocfg.autopilot_calibration)
        # Library-level events (engine program registrations,
        # generate's compile-cache misses) land in this run's JSONL;
        # the program registry arms under the same sink-configured
        # condition the training Observatory uses.
        registry_mod.set_active(self.registry)
        self.programs_armed = bool(sinks) and bool(ocfg.programs)
        if self.programs_armed:
            device_mod.set_enabled(True)

    def scheduler_kwargs(self) -> Dict[str, Any]:
        """The scheduler-facing slice of this bundle (serve/run.py
        splats it into the Scheduler ctor)."""
        return {
            "registry": self.registry, "tracer": self.tracer,
            "slo_monitor": self.slo_monitor,
            "anomaly_hub": self.anomalies,
            "autopilot": self.autopilot,
            "export_every": self.export_every,
            "export_path": self.export_path,
            "status_every": self.status_every,
        }

    def emit_device_time(self, profile_dir: str,
                         calibration: str = "") -> list:
        """Device-time attribution for a serve capture (see
        :func:`_emit_device_time`) — call before :meth:`close`."""
        return _emit_device_time(self.registry, profile_dir,
                                 calibration)

    def close(self) -> None:
        # A fatal exception funneling through serve_run's finally
        # (SlotRetryExhausted, StallError, ...) dumps the postmortem
        # bundle before the sinks close.
        _crash_dump(self.flightrec, self.registry)
        if self.programs_armed:
            device_mod.set_enabled(False)
        if registry_mod.get_active() is self.registry:
            registry_mod.set_active(None)
        if self.tracer is not None:
            self.tracer.close()
        self.registry.close()


class Observatory:
    """Run-scoped observability hub; build with :meth:`for_training`."""

    def __init__(self, ocfg=None, *, chief: bool = True,
                 tags: Optional[Dict[str, Any]] = None,
                 accountant: Optional[mfu_mod.ThroughputAccountant] = None,
                 items_per_step: float = 0.0,
                 process_index: int = 0,
                 append: bool = False,
                 clock=time.perf_counter,
                 run_config: Any = None):
        sinks = []
        window, max_records, trace_path = 200, 100_000, ""
        self.flightrec = None
        if ocfg is not None:
            if ocfg.metrics_jsonl:
                sinks.append(JsonlSink(ocfg.metrics_jsonl,
                                       append=append))
            if ocfg.metrics_csv:
                sinks.append(CsvSink(ocfg.metrics_csv,
                                     max_rows=ocfg.max_records))
            if getattr(ocfg, "flightrec", ""):
                # Crash flight recorder (observe/flightrec.py): rides
                # the registry as a sink; periodic fsync'd snapshots +
                # a postmortem dump on trappable deaths (see close()).
                self.flightrec = _build_flightrec(ocfg, tags,
                                                  run_config)
                sinks.append(FlightRecorderSink(self.flightrec))
            window, max_records = ocfg.window, ocfg.max_records
            trace_path = ocfg.trace
        self.registry = MetricsRegistry(
            sinks, enabled=chief, tags=tags or {},
            max_records=max_records,
            # --check arms per-record schema validation: every emit is
            # checked against observe/schemas.py and a violation
            # raises instead of landing in the artifact.
            validate=bool(getattr(run_config, "check", False)))
        # Online anomaly detection (observe/anomaly.py): fed from
        # log_step / health records below — values the loop already
        # fetched; zero new host transfers.
        self.anomalies = None
        if ocfg is not None and getattr(ocfg, "anomaly", False):
            self.anomalies = AnomalyHub(emit=self.registry.emit,
                                        window=ocfg.anomaly_window,
                                        phase="train")
        self.tracer = ChromeTracer(trace_path, pid=process_index,
                                   enabled=chief,
                                   process_name="tfd-train-host",
                                   clock=clock)
        # Active only when something consumes the output — the loop
        # calls every hook unconditionally and relies on this gate.
        self.active = bool(sinks) or self.tracer.enabled
        self.steptime = StepTimeBreakdown(window=window, clock=clock)
        self.goodput = GoodputCounter(clock=clock)
        self.accountant = accountant or mfu_mod.ThroughputAccountant()
        self.items_per_step = items_per_step
        self._clock = clock
        self._last_log: Optional[tuple] = None  # (step, clock)
        # Compiled-program registration (observe/device.py) arms only
        # for runs with a SINK: the AOT pass costs one extra trace per
        # program, which is worth paying exactly when a sink will
        # carry the compile records (a trace-only run has nowhere
        # durable for them — serve/run.py gates on the same
        # condition).
        self._programs = bool(sinks) and bool(
            getattr(ocfg, "programs", True) if ocfg is not None
            else True)
        if self.active:
            goodput_mod.set_active(self.goodput)
            # Library-level recovery events (checkpoint retries,
            # quarantines, watchdog stalls) flow to the same sinks.
            registry_mod.set_active(self.registry)
        if self._programs:
            device_mod.set_enabled(True)

    # -- construction -----------------------------------------------------
    @classmethod
    def for_training(cls, cfg, mesh, task=None, model=None, params=None,
                     chief: bool = True) -> "Observatory":
        """Build from a TrainConfig + live mesh/task/model/params."""
        import jax

        seq = None
        if task is not None and task.seq_axis is not None:
            seq = int(task.sample_input.shape[task.seq_axis])
        model_cfg = getattr(model, "cfg", None)
        fpi, unit = mfu_mod.flops_per_item(cfg.model, params, model_cfg,
                                           seq_len=seq)
        peak_dev = (cfg.observe.peak_tflops * 1e12
                    if cfg.observe.peak_tflops > 0
                    else mfu_mod.device_peak_flops())
        peak_total = peak_dev * len(jax.devices()) if peak_dev else None
        accountant = mfu_mod.ThroughputAccountant(
            flops_per_item=fpi, unit=unit, peak_flops_total=peak_total)
        # A resumed (preempt-restart) run APPENDS to the prior leg's
        # JSONL instead of truncating it — the pre-preemption records
        # are the artifact's point. Keyed to an ACTUAL restore (the
        # same condition train.loop restores under), not the flag
        # alone: schedulers pass --resume on every leg, and the first
        # leg of a fresh run must still replace a stale file.
        append = False
        if cfg.resume and cfg.checkpoint_dir:
            from tensorflow_distributed_tpu.train.checkpoint import (
                latest_step)
            append = latest_step(cfg.checkpoint_dir) is not None
        obs = cls(cfg.observe, chief=chief,
                  tags=host_tags(mesh, cfg), accountant=accountant,
                  items_per_step=float(cfg.batch_size) * (seq or 1),
                  process_index=jax.process_index(), append=append,
                  run_config=cfg)
        obs.seq_len = seq
        return obs

    def note_grad_sync(self, comm_bytes_per_step: float,
                       plan: Optional[Dict[str, Any]] = None) -> None:
        """Arm the per-step collective-exposed-vs-hidden estimate
        (grad_sync=overlap): ``comm_bytes_per_step`` is the overlap
        plan's per-device traffic (parallel.overlap.comm_bytes_per_
        step). Step records then carry ``comm_ms_est`` (traffic over
        the device kind's ICI bandwidth — the planner's TPU_HW table,
        generic ratios on unknown kinds) and, when the accountant
        knows the model FLOPs AND the chip peak, ``comm_exposed_ms_
        est``/``comm_hidden_ms_est``: the slice of the comm estimate
        NOT covered by the measured p50 step time's compute headroom.
        An estimate by construction — the A/B truth lives in
        benchmarks/gradsync.py."""
        if not self.active:
            return
        self._comm_bytes = float(comm_bytes_per_step)
        # Lazy: analysis.planner.score is import-light, but hub must
        # not pull it (or jax device queries) for runs that never arm
        # this.
        import jax

        from tensorflow_distributed_tpu.analysis.planner.score import (
            GENERIC_HW, TPU_HW)
        kind = getattr(jax.devices()[0], "device_kind", "unknown")
        self._ici_bw = TPU_HW.get(kind, GENERIC_HW)[1]
        if plan:
            self.emit("grad_sync", comm_bytes_per_step=self._comm_bytes,
                      ici_bw=self._ici_bw, **plan)

    def _comm_fields(self, step_ms: Optional[float]) -> Dict[str, Any]:
        """The exposed-vs-hidden split for one step-time sample."""
        comm_bytes = getattr(self, "_comm_bytes", 0.0)
        if not comm_bytes:
            return {}
        comm_ms = 1e3 * comm_bytes / self._ici_bw
        out = {"comm_ms_est": round(comm_ms, 4)}
        acc = self.accountant
        if (acc.flops_per_item and acc.peak_flops_total
                and self.items_per_step and step_ms is not None):
            compute_ms = (1e3 * acc.flops_per_item * self.items_per_step
                          / acc.peak_flops_total)
            exposed = min(comm_ms, max(0.0, step_ms - compute_ms))
            out["comm_exposed_ms_est"] = round(exposed, 4)
            out["comm_hidden_ms_est"] = round(comm_ms - exposed, 4)
        return out

    def note_step_fn(self, step_fn, params=None, model_cfg=None) -> None:
        """Inspect the built step function for observability metadata:
        a 1F1B step whose ``observe_hw_recompute`` attribute is set
        (train.pipeline_step) executes ~4x-forward for the block stack,
        so hw-MFU is reported alongside model MFU."""
        if (getattr(step_fn, "observe_hw_recompute", False)
                and self.accountant.flops_per_item
                and params is not None and "blocks" in params):
            self.accountant.hw_flops_per_item = (
                mfu_mod.pipelined_hw_flops_per_token(
                    params, model_cfg,
                    seq_len=getattr(self, "seq_len", None)))

    # -- per-step phase hooks (the loop's hot path) -----------------------
    @contextlib.contextmanager
    def data(self) -> Iterator[None]:
        if not self.active:
            yield
            return
        self.steptime.data_start()
        with self.tracer.span("data"):
            yield
        self.steptime.data_end()

    @contextlib.contextmanager
    def dispatch(self) -> Iterator[None]:
        if not self.active:
            yield
            return
        with self.tracer.span("dispatch"):
            yield
        self.steptime.dispatch_end()

    @contextlib.contextmanager
    def device_wait(self) -> Iterator[None]:
        if not self.active:
            yield
            return
        with self.tracer.span("device_wait"):
            yield
        self.steptime.device_end()

    def step_end(self) -> None:
        if self.active:
            self.steptime.step_end()

    # -- phase spans ------------------------------------------------------
    def phase(self, name: str):
        """Trace span + goodput charge for non-step phases the loop
        enters (eval, checkpoint, restore, drain). Goodput's nested-
        suppression keeps the inner train.checkpoint hooks from
        double-charging."""
        if not self.active:
            return contextlib.nullcontext()
        stack = contextlib.ExitStack()
        stack.enter_context(self.tracer.span(name))
        stack.enter_context(self.goodput.account(name))
        return stack

    def instant(self, name: str, **args: Any) -> None:
        self.tracer.instant(name, **args)

    # -- emission ---------------------------------------------------------
    def emit(self, event: str, **fields: Any) -> None:
        if not self.active:
            return
        if self.anomalies is not None and event == "health":
            # Per-module vitals tee into the anomaly hub (grad-norm
            # explosion / update-ratio collapse) — the values were
            # already fetched on the health cadence; anomaly records
            # flow out through the hub's own registry emit.
            self.anomalies.observe_health(
                int(fields.get("step", 0)),
                str(fields.get("module", "")), fields)
        self.registry.emit(event, **fields)

    def log_step(self, step: int, metrics: Dict[str, float]) -> None:
        """Per-cadence record: task metrics + rolling step-time
        breakdown + throughput/MFU over the window since the previous
        cadence log."""
        if not self.active:
            return
        now = self._clock()
        prev_log = self._last_log
        fields: Dict[str, Any] = {"step": step}
        fields.update({k: float(v) for k, v in metrics.items()})
        fields.update(self.steptime.summary())
        fields.update(self._comm_fields(fields.get("step_ms_p50")))
        if prev_log is not None:
            last_step, last_t = prev_log
            rates = self.accountant.rates(
                (step - last_step) * self.items_per_step, now - last_t)
            fields.update(rates)
            if "mfu" in rates:
                self.tracer.counter("mfu", mfu=rates["mfu"])
            key = f"{self.accountant.unit}s_per_sec"
            if key in rates:
                self.tracer.counter("throughput", **{key: rates[key]})
        self._last_log = (step, now)
        self.registry.emit("step", **fields)
        if self.anomalies is not None:
            # Detectors consume exactly what this cadence already
            # fetched: the task metrics (loss, grad_norm), the window
            # throughput, and the cadence-derived per-step wall.
            wall_ms = None
            if prev_log is not None and step > prev_log[0]:
                wall_ms = 1e3 * (now - prev_log[1]) / (step
                                                       - prev_log[0])
            self.anomalies.observe_train_step(step, fields,
                                              step_wall_ms=wall_ms)

    def summarize(self, total_seconds: Optional[float] = None,
                  **fields: Any) -> None:
        """Final 'summary' record: rolling stats + goodput ledger +
        caller-supplied run totals."""
        if not self.active:
            return
        # Process-level HBM budget rollup over the registered compiled
        # programs — the "how much must stay resident" companion to
        # the per-program compile records.
        if self._programs:
            budget = device_mod.hbm_budget()
            if budget:
                self.registry.emit("hbm_budget", **budget)
        # Plain dict merge (caller fields win): the goodput ledger may
        # carry categories whose "<cat>_seconds" keys the caller also
        # reports (e.g. compile_seconds from the loop's Timer).
        steps = self.steptime.summary()
        rec = {**steps, **self._comm_fields(steps.get("step_ms_p50")),
               **self.goodput.summary(total_seconds), **fields}
        self.registry.emit("summary", **rec)

    def emit_device_time(self, profile_dir: str,
                         calibration: str = "") -> list:
        """Device-time attribution after a profiler window closed
        (train/loop.py calls this once the StepProfiler stopped):
        parse the capture, join roofline predictions, emit
        ``device_time`` records (see :func:`_emit_device_time`)."""
        if not self.active:
            return []
        return _emit_device_time(self.registry, profile_dir,
                                 calibration)

    # -- lifecycle --------------------------------------------------------
    def flush(self) -> None:
        """Durable partial artifacts (the loop's exception path)."""
        if self.active:
            self.tracer.flush()

    def close(self) -> None:
        # Fatal exceptions (non-finite halt, recovery-budget
        # exhaustion, stall) all funnel through the loop's
        # ``finally: obs.close()`` — dump the postmortem bundle while
        # the exception is still in flight, before the sinks close.
        _crash_dump(self.flightrec, self.registry)
        if self._programs:
            device_mod.set_enabled(False)
        if goodput_mod.get_active() is self.goodput:
            goodput_mod.set_active(None)
        if registry_mod.get_active() is self.registry:
            registry_mod.set_active(None)
        self.tracer.close()
        self.registry.close()
