"""Declarative telemetry record schemas — the cross-process contract.

Every ``event=`` record kind the framework emits (through
``observe.registry`` or the stdout run log) is declared here once:
field name, type, required/optional, explicit-null allowed. Three
things consume the table:

* ``analysis/schema.py`` — the static pass that checks literal dict
  keys at every emit site (producers) and every field read in the
  report/regress/fleetview/router consumers against these schemas.
* ``MetricsRegistry(validate=True)`` — runtime validation, armed by
  ``--check``: an emit whose record violates its schema raises
  immediately instead of poisoning the JSONL stream.
* ``RECORDS.md`` — regenerated verbatim from this registry
  (``python -m tensorflow_distributed_tpu.analysis.schema --update``),
  so the doc can never drift from the declared contract.

Pure stdlib on purpose: the lint tier and the supervisor import this
without jax present.

Conventions
-----------
* ``required`` fields must be present on every record of the kind.
* ``nullable`` fields may be explicitly ``null`` (never absent when
  the producer promises shape stability — see RECORDS.md preamble).
* ``patterns`` declare open field FAMILIES (``val_<metric>``,
  ``coll_<family>_ms``, per-class ``ttft_ms_p95_<class>``) that a
  closed field list cannot enumerate.
* ``open_fields=True`` marks rollup kinds (``step`` task metrics,
  ``serve_summary``, ``metrics_snapshot``, …) whose producers splat
  computed dicts; producers may add fields beyond the table, but
  consumers may still only read DECLARED fields — one-sided openness
  keeps the reader contract checkable.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Field", "Schema", "COMMON_TAGS", "SCHEMAS", "NESTED",
    "RECOVERY_KINDS", "schema_for", "allowed_fields",
    "consumer_universe", "validate_record", "render_records_md",
]


@dataclasses.dataclass(frozen=True)
class Field:
    """One declared record field."""

    name: str
    type: str = "any"        # int|float|num|str|bool|dict|list|any
    required: bool = False
    nullable: bool = False
    doc: str = ""


def F(name: str, type: str = "any", required: bool = False,
      nullable: bool = False, doc: str = "") -> Field:
    return Field(name, type, required, nullable, doc)


@dataclasses.dataclass(frozen=True)
class Schema:
    """Contract for one record kind."""

    kind: str
    doc: str
    fields: Tuple[Field, ...]
    patterns: Tuple[str, ...] = ()
    open_fields: bool = False
    section: str = ""
    registry: bool = True    # False: stdout run-log only (no tags)

    def field_names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)


# Tags the registry stamps on every record (observe/registry.py emit).
COMMON_TAGS: Tuple[Field, ...] = (
    F("event", "str", required=True, doc="the record kind (sections below)"),
    F("t", "num", required=True,
      doc="seconds since the registry was built (run-relative)"),
    F("process_index", "int",
      doc="emitting host's `jax.process_index()` — the per-host grouping "
          "key `observe.report` splits sections on"),
    F("mesh", "str", doc="compact mesh shape, e.g. `\"data=8\"`"),
    F("config_hash", "str",
      doc="10-hex sha of the run config (`registry.config_hash`) — "
          "compare two streams run-to-run"),
)

# Keys observe.registry.write_jsonl stamps onto committed bench
# artifacts (not live registry events) — consumers may read them.
ARTIFACT_STAMP_FIELDS: Tuple[str, ...] = ("git_sha", "calibration_id")

# recovery.kind discriminator values (static pass checks literal kinds).
RECOVERY_KINDS: Tuple[str, ...] = (
    "fault_injected", "ckpt_retry", "quarantine", "rewind", "stall",
    "slot_quarantine", "weight_swap", "swap_skip", "restart",
    "mesh_change", "mesh_exhausted", "diverged_no_restart",
    "restart_budget_exhausted", "reshard_restore", "loss_spike",
    "nonfinite",
)

_SECTIONS: Tuple[Tuple[str, str], ...] = (
    ("Training", ""),
    ("Device telemetry (observe/device.py, observe/xprof.py)", ""),
    ("Planner (analysis/planner)", ""),
    ("Resilience", ""),
    ("Incident observatory (observe/anomaly.py, observe/flightrec.py)", ""),
    ("Serving", ""),
    ("Autopilot (observe/autopilot.py)",
     "The online controller's decision ledger (`--observe.autopilot`): "
     "every knob move (and every advisory it could not apply live) is "
     "one auditable `tune` record carrying the triggering signal, the "
     "observed value, and the threshold it crossed; one `tune_summary` "
     "rolls up the run (`quiet=true` is the well-tuned-run contract "
     "TUNEBENCH gates)."),
    ("Fleet serving (fleet/router.py, fleet/controller.py)",
     "Emitted by the FRONT-END process (fleet/run.py's registry), not "
     "the replicas; `observe.report` folds them into the Fleet section."),
    ("Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
     "Front-end records arming `--fleet.trace` / `--fleet.slo` / "
     "`--fleet.export-path`; `observe.report` folds them into the "
     "Fleet section's `slo`/`decomposition` entries."),
    ("Run log (stdout only)",
     "Written by `utils.logging.MetricLogger.log_json` to the human "
     "stdout stream only — never through the registry, so no common "
     "tags. Declared here so the same schema pass covers them."),
)

_SLO_FIELDS: Tuple[Field, ...] = (
    F("target", "str", required=True,
      doc="SLO target id, `<class>:<metric>:p<pct>`"),
    F("slo_class", "str", doc="request class the target scores"),
    F("metric", "str", doc="latency metric (`ttft_ms` / `tok_ms`)"),
    F("pct", "num", doc="target percentile"),
    F("threshold_ms", "num", doc="latency threshold for the percentile"),
    F("burn_fast", "num", doc="fast-window error-budget burn rate"),
    F("burn_slow", "num", doc="slow-window error-budget burn rate"),
    F("window_fast", "int", doc="fast window length (decode steps)"),
    F("window_slow", "int", doc="slow window length (decode steps)"),
    F("budget_remaining", "num", doc="error budget remaining, 0..1"),
    F("step", "int", doc="decode-step clock at the transition"),
)

SCHEMAS: Tuple[Schema, ...] = (
    # ---------------------------------------------------------- Training
    Schema(
        "start", section="Training",
        doc="One per run.",
        fields=(
            F("model", "str", required=True, doc="model name from config"),
            F("task", "str", required=True, doc="task name"),
            F("params", "int", required=True, doc="parameter count"),
            F("global_batch", "int", doc="global batch size"),
            F("start_step", "int", doc="first step of this leg (0 fresh)"),
            F("mesh", "dict",
              doc="mesh axes as a dict (stdout log only; the registry "
                  "copy rides the compact `mesh` tag instead)"),
        )),
    Schema(
        "step", section="Training", open_fields=True,
        doc="Per log cadence (`--log-every`). Open record: task metrics "
            "(`loss`, …) ride along beyond this table.",
        fields=(
            F("step", "int", required=True, doc="global step"),
            F("loss", "num", doc="task loss (task metrics are open)"),
            F("step_ms_p50", "num", doc="rolling step-time median"),
            F("step_ms_p95", "num", doc="rolling step-time p95"),
            F("data_ms", "num", doc="phase breakdown: host data wait"),
            F("dispatch_ms", "num", doc="phase breakdown: dispatch"),
            F("device_ms", "num", doc="phase breakdown: device wall"),
            F("tokens_per_sec", "num", doc="window throughput (LM tasks)"),
            F("images_per_sec", "num", doc="window throughput (vision)"),
            F("items_per_sec", "num", doc="window throughput (generic)"),
            F("model_tflops", "num", doc="model FLOP rate"),
            F("mfu", "num", doc="model FLOPs utilization"),
            F("hw_mfu", "num",
              doc="hardware MFU (counts recompute FLOPs, 1F1B)"),
            F("comm_ms_est", "num",
              doc="estimated collective traffic per step "
                  "(`--grad-sync overlap` only)"),
            F("comm_exposed_ms_est", "num",
              doc="estimated NON-overlapped collective wall"),
            F("comm_hidden_ms_est", "num",
              doc="estimated overlapped collective wall"),
        )),
    Schema(
        "eval", section="Training",
        doc="Cadence/final eval.",
        fields=(
            F("step", "int", required=True, doc="global step"),
            F("eval_seconds", "num", doc="eval wall seconds"),
        ),
        patterns=(r"val_\w+",)),
    Schema(
        "summary", section="Training", open_fields=True,
        doc="One per run: final rolling stats, goodput ledger, "
            "steady-state throughput. Open record: rolling stats and "
            "throughput rates ride along beyond this table.",
        fields=(
            F("steps", "int", doc="final global step"),
            F("preempted", "bool", doc="run ended on a preemption signal"),
            F("goodput", "num", doc="productive fraction of wall time"),
            F("train_seconds", "num", doc="total train wall"),
            F("compile_seconds", "num", doc="compile wall"),
            F("steps_per_sec", "num", doc="steady-state step rate"),
        ),
        patterns=(r"val_\w+", r"\w+_seconds")),
    Schema(
        "preempted", section="Training",
        doc="Lifecycle marker: the run checkpointed and exited on a "
            "preemption signal.",
        fields=(F("step", "int", required=True, doc="step at exit"),)),
    Schema(
        "resumed", section="Training",
        doc="Lifecycle marker: the run restored from a checkpoint; a "
            "resharded resume carries the mesh transition.",
        fields=(
            F("step", "int", required=True, doc="restored step"),
            F("from_mesh", "any",
              doc="mesh dict the checkpoint was saved on"),
            F("to_mesh", "any", doc="mesh dict restored onto"),
            F("reshard_seconds", "num", doc="reshard wall seconds"),
            F("per_device_batch", "int", doc="batch per device after"),
        )),
    Schema(
        "rewound", section="Training", registry=False,
        doc="Lifecycle marker (stdout): the loop rewound to an earlier "
            "checkpoint (the registry twin is `recovery` kind=`rewind`).",
        fields=(F("step", "int", required=True, doc="step rewound to"),)),
    # ------------------------------------------------- Device telemetry
    Schema(
        "compile", section="Device telemetry (observe/device.py, observe/xprof.py)",
        doc="One per instrumented program registration.",
        fields=(
            F("program", "str", required=True, doc="instrumented program name"),
            F("flops", "num", nullable=True, doc="cost analysis: FLOPs"),
            F("bytes_accessed", "num", nullable=True,
              doc="cost analysis: bytes accessed"),
            F("argument_bytes", "int", nullable=True,
              doc="memory analysis: argument bytes"),
            F("output_bytes", "int", nullable=True,
              doc="memory analysis: output bytes"),
            F("temp_bytes", "int", nullable=True,
              doc="memory analysis: temp bytes"),
            F("generated_code_bytes", "int", nullable=True,
              doc="memory analysis: generated code bytes"),
            F("donated_bytes", "int", nullable=True,
              doc="bytes of donated (aliased) arguments"),
            F("peak_hbm_bytes", "int", nullable=True,
              doc="peak HBM estimate for the program"),
            F("lower_s", "num", doc="lowering wall seconds"),
            F("compile_s", "num", doc="compile wall seconds"),
            F("error", "str",
              doc="only on degraded registration: why costs are missing"),
        )),
    Schema(
        "compile_cache",
        section="Device telemetry (observe/device.py, observe/xprof.py)",
        open_fields=True,
        doc="A compiled-program cache MISS in `models/generate.py`'s "
            "sampler factories. Open record: per-program miss counters "
            "ride along.",
        fields=(
            F("program", "str", required=True, doc="program family name"),
            F("result", "str", doc="cache outcome (`miss`, …)"),
        )),
    Schema(
        "hbm_budget",
        section="Device telemetry (observe/device.py, observe/xprof.py)",
        doc="Process rollup over registered programs.",
        fields=(
            F("programs", "int", required=True, doc="registered programs"),
            F("peak_hbm_bytes_max", "int", nullable=True,
              doc="max single-program peak"),
            F("peak_hbm_bytes_sum", "int", nullable=True,
              doc="all-resident worst case"),
        )),
    Schema(
        "device_time",
        section="Device telemetry (observe/device.py, observe/xprof.py)",
        doc="Ground-truth device wall per program, parsed from the "
            "profiler's Perfetto export after a `--profile-dir` window "
            "closes (`observe/xprof.py`).",
        fields=(
            F("program", "str", nullable=True,
              doc="instrumented program name (`null` for unmatched modules)"),
            F("module", "str", nullable=True,
              doc="XLA module the ops carried (`jit_<program>`)"),
            F("device_ms", "num", nullable=True,
              doc="union of op intervals over the window (concurrent "
                  "lanes counted once)"),
            F("device_ms_per_call", "num", nullable=True,
              doc="`device_ms / calls`"),
            F("op_ms", "num", nullable=True, doc="plain sum of op durations"),
            F("calls", "int", nullable=True,
              doc="estimated invocations in the window (modal per-op "
                  "occurrence count)"),
            F("collective_ms", "num", nullable=True,
              doc="union of collective-op intervals"),
            F("exposed_collective_ms", "num", nullable=True,
              doc="collective wall NOT overlapped by same-module compute "
                  "— the measured counterpart of `comm_exposed_ms_est`"),
            F("coarse", "bool",
              doc="true when the trace had no `/device:` timeline "
                  "(CPU: host-threadpool walls)"),
            F("predicted_ms_per_call", "num", nullable=True,
              doc="roofline prediction from the program's `compile` "
                  "costs (when joinable)"),
            F("calibration_id", "str", nullable=True,
              doc="profile that predicted (null = static tables)"),
            F("reason", "str",
              doc="only on explicit-null records: why nothing was "
                  "attributable"),
        ),
        patterns=(r"coll_\w+_ms",)),
    Schema(
        "health",
        section="Device telemetry (observe/device.py, observe/xprof.py)",
        doc="Per-module on-device vitals on the health cadence.",
        fields=(
            F("module", "str", required=True, doc="instrumented module"),
            F("step", "int", required=True, doc="global step"),
            F("grad_norm", "num", doc="gradient norm"),
            F("update_ratio", "num", doc="update/param RMS ratio"),
            F("param_rms", "num", doc="parameter RMS"),
            F("act_rms", "num", doc="activation RMS (when instrumented)"),
        )),
    # ------------------------------------------------------------ Planner
    Schema(
        "plan", section="Planner (analysis/planner)", open_fields=True,
        doc="The `--plan auto` choice. Open record: planner diagnostics "
            "ride along.",
        fields=(
            F("family", "str", doc="model family planned for"),
            F("size", "str", doc="model size"),
            F("devices", "int", doc="device count planned for"),
            F("batch_size", "int", doc="global batch planned for"),
            F("mesh", "str", doc="chosen mesh"),
            F("strategy", "str", doc="chosen strategy"),
            F("partition", "str", doc="chosen partition"),
            F("predicted_step_ms", "num", doc="cost-model step prediction"),
            F("predicted_peak_hbm_bytes", "int", doc="cost-model HBM peak"),
            F("candidates", "int", doc="layouts scored"),
            F("feasible", "int", doc="layouts under the HBM budget"),
            F("infeasible", "int", doc="layouts over the HBM budget"),
            F("pruned", "int", doc="layouts pruned before scoring"),
            F("calibration_id", "str", nullable=True,
              doc="calibration profile used (null = static tables)"),
        )),
    Schema(
        "plan_drift", section="Planner (analysis/planner)",
        doc="Emitted at run end when a plan record exists and a "
            "steady-state p50 was measured — the cost model's error on "
            "this very run, the signal a calibration refit "
            "(`analysis/planner/calibrate.py`) consumes.",
        fields=(
            F("predicted_step_ms", "num", required=True,
              doc="the plan's prediction"),
            F("measured_step_ms_p50", "num", required=True,
              doc="measured steady-state p50"),
            F("drift_ratio", "num", required=True, doc="measured/predicted"),
            F("calibration_id", "str", nullable=True,
              doc="profile that predicted (null = static tables)"),
        )),
    Schema(
        "grad_sync", section="Planner (analysis/planner)", open_fields=True,
        doc="The overlap bucket plan at startup. Open record: "
            "bucket-plan fields ride along.",
        fields=(
            F("comm_bytes_per_step", "int", required=True,
              doc="estimated collective bytes per step"),
            F("ici_bw", "num", doc="assumed interconnect bandwidth"),
            F("axis_size", "int", doc="data-axis size"),
            F("bucket_bytes", "int", doc="bucket size"),
            F("scatter_buckets", "int", doc="reduce-scatter buckets"),
            F("replicated_buckets", "int", doc="all-reduce buckets"),
            F("scatter_bytes", "int", doc="reduce-scatter bytes"),
            F("replicated_bytes", "int", doc="all-reduce bytes"),
            F("leaves", "int", doc="gradient leaves bucketed"),
        )),
    # --------------------------------------------------------- Resilience
    Schema(
        "recovery", section="Resilience",
        doc="Every fault/containment action, discriminated by `kind`: "
            + ", ".join(f"`{k}`" for k in RECOVERY_KINDS)
            + ". Kind-specific fields ride along (table below is the "
              "union across kinds).",
        fields=(
            F("kind", "str", required=True, doc="the discriminator"),
            F("step", "int", doc="global/decode step at the action"),
            F("fault", "str", doc="fault_injected: injected fault id"),
            F("slot", "int", doc="slot index (slot faults/quarantine)"),
            F("rid", "str", doc="request id (slot_quarantine)"),
            F("retry", "int", doc="slot_quarantine: retry count"),
            F("seconds", "num",
              doc="wall seconds (stalls, weight_swap, reshard_restore)"),
            F("t_s", "num", doc="serve clock seconds"),
            F("attempt", "int", doc="ckpt_retry: attempt number"),
            F("budget", "int", doc="retry/skip budget"),
            F("error", "str", doc="ckpt_retry: exception text"),
            F("backoff_s", "num", doc="backoff before the retry/restart"),
            F("reason", "str", doc="why (quarantine, swap_skip, nonfinite)"),
            F("mesh", "str", doc="quarantine: mesh after masking"),
            F("from_step", "int", doc="rewind: step rewound from"),
            F("to_step", "int", doc="rewind: step rewound to"),
            F("from_mesh", "any", doc="mesh before (mesh_change/reshard)"),
            F("to_mesh", "any", doc="mesh after (mesh_change/reshard)"),
            F("resharded", "bool",
              doc="reshard_restore: topology actually changed"),
            F("what", "str", doc="stall: watched phase (data/sync)"),
            F("timeout_s", "num", doc="stall: the tripped timeout"),
            F("multihost", "bool", doc="stall: multihost run"),
            F("loss", "num", doc="loss_spike/nonfinite: offending loss"),
            F("window_median", "num", doc="loss_spike: rolling median"),
            F("action", "str", doc="nonfinite: policy action taken"),
            F("used", "int", doc="nonfinite: budget used"),
            F("ckpt_step", "int", doc="weight_swap: step swapped in"),
            F("leg", "int", doc="supervisor: leg number"),
            F("rc", "int", doc="supervisor: dead leg's return code"),
            F("restarts", "int", doc="supervisor: restarts so far"),
            F("alive", "int", doc="supervisor: alive device count"),
            F("masked", "int", doc="supervisor: masked device count"),
            F("bundle", "str",
              doc="supervisor: dead leg's postmortem bundle path"),
            F("resume", "bool", doc="supervisor: next leg resumes"),
            F("lost", "int", doc="fault_injected device_loss: lost count"),
            F("mask_file", "str",
              doc="fault_injected device_loss: device-mask path"),
            F("failures", "int", doc="fault_injected ckpt_io_fail: count"),
        )),
    # ------------------------------------------------ Incident observatory
    Schema(
        "anomaly",
        section="Incident observatory (observe/anomaly.py, observe/flightrec.py)",
        doc="One per detection, emitted the moment a streaming detector "
            "leaves its envelope (`--observe.anomaly`; fed from values "
            "already fetched on the log cadence — train — or the "
            "decode-step clock — serve). The live rollup (total count, "
            "per-detector counts, currently-`active` detectors, `last` "
            "anomaly) rides `metrics_snapshot` records and the "
            "`--observe.export-path` payload under the `anomaly` key.",
        fields=(
            F("detector", "str", required=True,
              doc="detector id: `loss_nonfinite`, `loss_spike`, "
                  "`loss_plateau`, `step_time_spike`, `throughput_slope`, "
                  "`grad_norm_spike[/module]`, "
                  "`update_ratio_collapse/<module>`, `ttft_spike`, "
                  "`decode_time_spike`, `queue_growth`, `slot_nonfinite`"),
            F("severity", "str", required=True,
              doc="`warn` (degradation) or `critical` (active damage: "
                  "non-finite values, explosions)"),
            F("step", "int",
              doc="the phase's clock at detection (train step / decode "
                  "step)"),
            F("value", "num", nullable=True, doc="the offending sample"),
            F("baseline", "num", nullable=True,
              doc="rolling baseline (median) it broke from"),
            F("zscore", "num", nullable=True,
              doc="robust MAD z-score (spike detectors)"),
            F("evidence", "list",
              doc="the last few window samples behind the baseline"),
            F("module", "str", nullable=True,
              doc="module context (per-module detectors)"),
            F("slot", "int", nullable=True,
              doc="slot context (per-slot detectors)"),
            F("rid", "str", nullable=True,
              doc="request context (per-slot detectors)"),
        )),
    Schema(
        "postmortem",
        section="Incident observatory (observe/anomaly.py, observe/flightrec.py)",
        doc="Emitted when a fatal exception funnels through the run's "
            "``finally`` (non-finite halt, recovery-budget exhaustion, "
            "stall) and the flight recorder dumps its bundle. Signal "
            "deaths leave no registry record — a SIGTERM writes the "
            "same bundle FILE from its handler before the process dies, "
            "a SIGKILL leaves only the last fsync'd "
            "`flight-<pid>.jsonl` snapshot — and the supervisor's "
            "`restart` recovery event carries the dead leg's bundle "
            "path as `bundle` either way. Render any flavor with "
            "`python -m tensorflow_distributed_tpu.observe.postmortem "
            "<bundle>`.",
        fields=(
            F("bundle", "str", required=True,
              doc="the `postmortem-<pid>.jsonl` path"),
            F("reason", "str", required=True,
              doc="exception class + message"),
        )),
    # ------------------------------------------------------------ Serving
    Schema(
        "serve_request", section="Serving",
        doc="One per completed request.",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("prompt_len", "int", doc="prompt tokens"),
            F("new_tokens", "int", doc="generated tokens"),
            F("finish", "str", doc="`eos` or `budget`"),
            F("ttft_ms", "num", nullable=True, doc="time to first token"),
            F("tok_ms", "num", nullable=True, doc="mean inter-token ms"),
            F("queue_steps", "int", doc="decode steps spent queued"),
            F("retries", "int", doc="intake retries"),
            F("preempts", "int", doc="times preempted by the scheduler"),
            F("slo", "str", doc="SLO class"),
            F("tenant", "str", nullable=True, doc="tenant id"),
            F("recovery_window", "bool",
              doc="arrival→first-token overlapped a recovery event"),
            F("arrival_s", "num", doc="serve-clock arrival stamp"),
            F("t_first_s", "num", nullable=True,
              doc="serve-clock first-token stamp"),
        )),
    Schema(
        "serve_summary", section="Serving", open_fields=True,
        doc="One per serve run. Open record: speculation fields "
            "(`spec_tokens`, `verify_steps`, `accept_rate`, "
            "`spec_fallback_slots`), the SLO monitor rollup "
            "(`slo_alerts`, `slo_budget_remaining_min`, `slo_targets`) "
            "and — on a paged run (`--serve.paged`) — the paging rollup "
            "(`page_size`, `num_pages`, `page_bytes`, "
            "`pages_per_max_len`, `pages_in_use`, `pages_peak`, "
            "`slot_pages_peak`, `pool_occupancy`, `prefix_hits`, "
            "`prefix_hit_tokens`, `prefix_hit_rate`, `prompt_tokens`, "
            "`prefill_tokens_computed`, `prefill_tokens_dense`, "
            "`cow_copies`, `page_evictions`, `cached_pages`, "
            "`sessions`) ride along.",
        fields=(
            F("requests", "int", doc="completed requests"),
            F("total_new_tokens", "int", doc="tokens generated"),
            F("wall_s", "num", doc="serve wall seconds"),
            F("tokens_per_sec", "num", doc="decode throughput"),
            F("mean_slot_occupancy", "num", doc="mean live-slot fraction"),
            F("prefill_compiles", "int", doc="prefill bucket compiles"),
            F("buckets", "list", doc="prefill bucket sizes"),
            F("retries", "int", doc="intake retries"),
            F("swaps", "int", doc="weight swaps absorbed"),
            F("swap_seconds", "num", doc="wall spent swapping"),
            F("seed", "int", doc="sampler seed"),
            F("trace", "str", nullable=True, doc="Perfetto trace path"),
            F("resumed", "int", doc="requests resumed from the journal"),
            F("policy", "str", doc="scheduler policy"),
            F("preemptions", "int", doc="scheduler preemptions"),
            F("anomalies", "int",
              doc="total anomaly-record count (when `--observe.anomaly` "
                  "is armed)"),
            F("tune_actions", "int",
              doc="applied autopilot knob changes this run (when "
                  "`--observe.autopilot` is armed; 0 on a well-tuned "
                  "run — the quiet-control contract)"),
            F("tp_width", "int",
              doc="tensor-parallel width (`--serve.mesh-model`, 1 when "
                  "unsharded)"),
            F("per_device_cache_bytes", "int",
              doc="slot cache's PER-DEVICE resident bytes — already "
                  "divided by the TP width, so a router summing replicas "
                  "never counts one sharded cache N times"),
            F("engine_mesh", "dict",
              doc="engine's mesh shape as a dict, e.g. "
                  "`{\"data\": 1, \"model\": 2}` — distinct from the "
                  "registry's compact `mesh` host tag"),
        ),
        patterns=(r"ttft_ms_p\d+(_\w+)?",)),
    Schema(
        "prefix_hit", section="Serving",
        doc="One per paged admission whose prompt matched cached pages "
            "(serve/paging).",
        fields=(
            F("slot", "int", required=True, doc="admitted slot"),
            F("prompt_len", "int", doc="prompt tokens"),
            F("hit_tokens", "int",
              doc="matched prefix length — prefill ran only on the rest"),
            F("tail_bucket", "int",
              doc="the bucket the tail actually computed"),
            F("session", "str", nullable=True,
              doc="conversation id on a session re-attach, else null"),
        )),
    Schema(
        "page_evict", section="Serving",
        doc="LRU eviction under pool pressure (an admission needed more "
            "pages than were free).",
        fields=(
            F("evicted", "int", required=True,
              doc="entries released this acquire"),
            F("reason", "str", doc="eviction reason"),
            F("pages_free", "int", doc="free pages after"),
            F("pages_in_use", "int", doc="in-use pages after"),
        )),
    Schema(
        "slo_alert", section="Serving",
        doc="Burn-rate alert transition on the decode-step clock "
            "(`observe/slo.py`).",
        fields=_SLO_FIELDS),
    Schema(
        "slo_ok", section="Serving",
        doc="Burn-rate recovery transition (the alert cleared).",
        fields=_SLO_FIELDS),
    Schema(
        "metrics_snapshot", section="Serving", open_fields=True,
        doc="Rolling point-in-time export (`--observe.export-every`; "
            "also atomically rewritten at `--observe.export-path`). "
            "Open record: the SLO state and — on a paged run — the "
            "paged rollup (same fields as `serve_summary`'s) ride "
            "along. `ckpt_step` (when serving restored weights) is the "
            "trained step the live params came from — the fleet "
            "controller's model-staleness feed; `draining` appears once "
            "a drain command landed.",
        fields=(
            F("seq", "int", required=True,
              doc="monotonic snapshot sequence — liveness triplet for "
                  "pollers (fleet/router.py): a frozen file is "
                  "distinguishable from a healthy idle replica"),
            F("wall_ts", "num", required=True,
              doc="liveness triplet: time.time() at the write"),
            F("pid", "int", doc="liveness triplet: emitting pid"),
            F("t_s", "num", doc="serve clock seconds"),
            F("decode_steps", "int", doc="decode steps so far"),
            F("requests_done", "int", doc="completed requests"),
            F("requests_live", "int", doc="live requests"),
            F("queue_depth", "int", doc="queued requests"),
            F("slot_occupancy", "num", doc="live-slot fraction"),
            F("tokens_per_sec", "num", doc="cumulative throughput"),
            F("tokens_per_sec_window", "num",
              doc="throughput over the rolling window — beside the "
                  "cumulative rate, so a regime shift is visible to a "
                  "controller (the autopilot reads this one)"),
            F("accept_rate", "num", nullable=True,
              doc="speculation accept rate, lifetime-cumulative"),
            F("accept_rate_window", "num",
              doc="accept rate over the rolling window "
                  "(accepted/proposed deltas between the window "
                  "endpoints) — the autopilot's loop-3 signal"),
            F("spec_tokens", "int",
              doc="CURRENT speculation depth k — moves live under "
                  "autopilot loop 3"),
            F("tune_actions", "int",
              doc="applied autopilot knob changes so far "
                  "(`--observe.autopilot`)"),
            F("retries", "int", doc="intake retries"),
            F("preemptions", "int", doc="scheduler preemptions"),
            F("swaps", "int", doc="weight swaps absorbed"),
            F("num_slots", "int", doc="capacity: decode slots"),
            F("max_len", "int", doc="capacity: max sequence length"),
            F("tp_width", "int", doc="capacity: tensor-parallel width"),
            F("per_device_cache_bytes", "int",
              doc="capacity: per-device cache bytes (see `serve_summary`)"),
            F("engine_mesh", "dict", doc="engine mesh dict"),
            F("ckpt_step", "int",
              doc="trained step the live params came from"),
            F("draining", "bool", doc="a drain command landed"),
            F("inbox_poll_lag_ms", "num",
              doc="intake-minus-`enq_ts` stamp over recent requests — "
                  "the decomposition's replica-side anchor and an early "
                  "warning for a wedged feed"),
            F("inbox_poll_lag_ms_p95", "num", doc="p95 of the same"),
            F("anomaly", "dict", doc="live anomaly rollup (see `anomaly`)"),
            F("slo", "dict", doc="live SLO state (see `NESTED`)"),
        ),
        patterns=(r"ttft_ms_p\d+(_\w+)?",)),
    Schema(
        "serve_cancel", section="Serving",
        doc="Fleet-replica intake outcome (`--serve.inbox`): the router "
            "moved the request elsewhere, dropped without a completion.",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("where", "str", required=True,
              doc="`queue` | `pending` | `live`"),
            F("slot", "int", doc="slot freed (live cancels)"),
        )),
    Schema(
        "serve_reject", section="Serving",
        doc="Fleet-replica intake outcome (`--serve.inbox`): the "
            "request cannot be served here (does not fit, or arrived "
            "while draining); a matching `reject` line lands in the "
            "journal so the router sheds instead of waiting.",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("prompt_len", "int", doc="prompt tokens"),
            F("max_new", "int", doc="requested generation budget"),
            F("draining", "bool", doc="rejected because draining"),
        )),
    Schema(
        "preempt", section="Serving",
        doc="SLO scheduler preempt-and-requeue (policy, NOT a recovery).",
        fields=(
            F("rid", "str", required=True, doc="victim request id"),
            F("slot", "int", doc="slot released"),
            F("slo", "str", doc="victim's SLO class"),
            F("tenant", "str", nullable=True, doc="victim's tenant"),
            F("served", "int", doc="tokens served before preemption"),
            F("t_s", "num", doc="serve clock seconds"),
        )),
    # ---------------------------------------------------------- Autopilot
    Schema(
        "tune", section="Autopilot (observe/autopilot.py)",
        doc="One autopilot decision: a live knob actuation "
            "(`applied=true` — routed through the scheduler's "
            "control-command path between decode steps, so the token "
            "streams are identical by construction) or an advisory "
            "recommendation for a boot-time knob it cannot change live "
            "(`applied=false`: `num_pages`, `buckets`, or a calibration "
            "refit with no `--observe.autopilot-calibration` path). The "
            "`signal`/`observed`/`threshold` triple plus `evidence` is "
            "the machine-readable audit trail TUNEBENCH gates.",
        fields=(
            F("step", "int", required=True,
              doc="decode-step clock at the decision"),
            F("loop", "str", required=True,
              doc="`admission` | `capacity` | `speculation` | "
                  "`calibration`"),
            F("knob", "str", required=True,
              doc="`decode_priority` | `slot_cap` | `spec_k` | "
                  "`calibration` | `num_pages` | `buckets`"),
            F("action", "str", required=True,
              doc="what moved: `tighten`/`relax` (admission), "
                  "`shrink`/`grow` (slot cap), `deepen`/`shallow` "
                  "(spec k), `refit` (calibration), `recommend` "
                  "(advisories)"),
            F("value", "any", required=True,
              doc="the new knob value (calibration: the refit "
                  "profile's `calibration_id`)"),
            F("prev", "any", nullable=True, doc="the value it replaced"),
            F("signal", "str", required=True,
              doc="the telemetry stream that triggered: "
                  "`slo_burn_fast` | `pool_occupancy` | "
                  "`accept_rate_window` | `drift_ratio` | "
                  "`slot_pages_peak` | `prompt_len_p99`"),
            F("observed", "num", doc="the signal's observed value"),
            F("threshold", "num", doc="the threshold it crossed"),
            F("applied", "bool", required=True,
              doc="true = actuated live through the control-command "
                  "path; false = advisory only"),
            F("evidence", "dict",
              doc="the triggering context (e.g. the `plan_drift` "
                  "record, burn rates per target, the sizer's "
                  "rationale lines)"),
        )),
    Schema(
        "tune_summary", section="Autopilot (observe/autopilot.py)",
        doc="One per autopilot-armed run: the decision-ledger rollup. "
            "`quiet=true` (zero applied actions) is the well-tuned-run "
            "contract; `suppressed` counts triggers absorbed by per-knob "
            "cooldowns (the rate limiter working, not a bug).",
        fields=(
            F("step", "int", required=True,
              doc="decode-step clock at run end"),
            F("evals", "int", doc="evaluation ticks"),
            F("actions", "int", doc="applied knob changes"),
            F("advisories", "int",
              doc="applied=false recommendations emitted"),
            F("suppressed", "int",
              doc="triggers absorbed by a cooling-down knob"),
            F("by_knob", "dict", doc="applied changes per knob"),
            F("quiet", "bool", required=True,
              doc="zero applied actions (the control-run gate)"),
        )),
    # ------------------------------------------------------ Fleet serving
    Schema(
        "fleet_dispatch",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        doc="One request handed to one replica.",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("replica", "int", required=True, doc="target replica"),
            F("kind", "str", doc="`fresh` | `redispatch`"),
            F("retry", "int", doc="re-dispatches so far"),
            F("slo", "str", doc="SLO class"),
            F("base_tokens", "int", doc="continuation length"),
            F("t_s", "num", doc="router clock seconds"),
        )),
    Schema(
        "fleet_shed",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        doc="Load shedding / retry exhaustion (shed, never hang).",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("slo", "str", doc="SLO class"),
            F("reason", "str",
              doc="`saturated` | `retry_budget` | `rejected`"),
            F("retries", "int", doc="re-dispatches before the shed"),
            F("t_s", "num", doc="router clock seconds"),
        )),
    Schema(
        "fleet_replica",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        doc="Replica lifecycle transition.",
        fields=(
            F("replica", "int", required=True, doc="replica index"),
            F("state", "str", required=True,
              doc="`spawned` | `up` | `quarantined` | `rejoined` | "
                  "`dead` | `restarted` | `exited` | "
                  "`diverged_no_restart` | `restart_budget_exhausted`"),
            F("reason", "str",
              doc="quarantine: `stale_snapshot` | `anomaly:<detector>`"),
            F("epoch", "int", doc="replica epoch (restarts bump it)"),
            F("rc", "int", doc="exit code (exited)"),
            F("inflight", "int", doc="requests in flight at the event"),
            F("restarts", "int", doc="restart count (budget exhaustion)"),
            F("t_s", "num", doc="controller/router clock seconds"),
        )),
    Schema(
        "fleet_swap",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        doc="Rolling weight swap, per replica acknowledgement "
            "(`state: timeout` when one never acked).",
        fields=(
            F("replica", "int", required=True, doc="replica index"),
            F("ckpt_step", "int", doc="step swapped in"),
            F("state", "str", doc="`timeout` when the ack never came"),
            F("t_s", "num", doc="controller clock seconds"),
        )),
    Schema(
        "fleet_roll",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        doc="Fleet-wide rollout lifecycle (`done_partial`: a replica "
            "timed out — NOT counted as a rolling swap).",
        fields=(
            F("state", "str", required=True,
              doc="`begin` | `done` | `done_partial` | `drain`"),
            F("ckpt_step", "int", doc="step rolled out"),
            F("replicas", "int", doc="replicas targeted (begin)"),
            F("timeouts", "int", doc="replicas that never acked"),
            F("t_s", "num", doc="controller clock seconds"),
        )),
    Schema(
        "fleet_summary",
        section="Fleet serving (fleet/router.py, fleet/controller.py)",
        open_fields=True,
        doc="One per fleet run: request totals, availability counters, "
            "TTFT percentiles, train→serve loop state. Open record: "
            "`shed_by_class`/`shed_reasons`/`dispatch_retry_hist` "
            "dicts, the fleet SLO rollup (`fleet_slo_alerts`, "
            "`fleet_slo_budget_remaining_min`, `fleet_slo_targets`), "
            "stitch stats (`stitch_sources`, `stitch_skipped`, "
            "`stitch_balanced`, `stitch_closed_at_death`, `fleet_trace` "
            "path) and decomposition coverage (`decomp_requests`, "
            "`decomp_residual_frac_mean`) ride along.",
        fields=(
            F("requests", "int", doc="requests accepted"),
            F("requests_done", "int", doc="requests completed"),
            F("requests_shed", "int", doc="requests shed"),
            F("requests_lost", "int", doc="requests lost (should be 0)"),
            F("dispatches", "int", doc="dispatch count"),
            F("redispatches", "int", doc="re-dispatch count"),
            F("quarantines", "int", doc="replica quarantines"),
            F("rejoins", "int", doc="replica rejoins"),
            F("deaths", "int", doc="replica deaths"),
            F("restarts", "int", doc="replica restarts"),
            F("recovery_requests", "int",
              doc="requests whose arrival→first-token window overlapped "
                  "a death/quarantine/timeout, or that were "
                  "re-dispatched"),
            F("rolling_swaps", "int", doc="fully-acked rollouts only"),
            F("partial_rolls", "int", doc="rollouts with a timeout"),
            F("swap_timeouts", "int", doc="per-replica ack timeouts"),
            F("rolled_step", "int", nullable=True,
              doc="last step rolled out"),
            F("staleness_max_steps", "int", nullable=True,
              doc="max model staleness observed (steps)"),
            F("replica_swaps", "int", doc="per-replica swap count"),
            F("replica_staleness_max", "int", nullable=True,
              doc="max per-replica staleness"),
            F("tokens_per_sec", "num", doc="fleet decode throughput"),
            F("wall_s", "num", doc="fleet wall seconds"),
            F("drained_clean", "bool", doc="drain completed cleanly"),
            F("timed_out", "bool", doc="run hit its wall-clock limit"),
            F("shed_by_class", "dict", doc="sheds per SLO class"),
            F("shed_reasons", "dict", doc="sheds per reason"),
            F("dispatch_retry_hist", "dict",
              doc="dispatch-count histogram per request"),
            F("fleet_trace", "str", nullable=True,
              doc="merged Perfetto file path (`--fleet.trace`)"),
            F("decomp_requests", "int",
              doc="requests the decomposition covered"),
            F("decomp_residual_frac_mean", "num", nullable=True,
              doc="mean residual fraction of the decomposition"),
        ),
        patterns=(r"ttft_ms_p\d+(_\w+)?",)),
    # -------------------------------------------------- Fleet observatory
    Schema(
        "fleet_request",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        doc="One per COMPLETED client request, the fleet-level twin of "
            "`serve_request` scored on client-perceived latency. This "
            "population drives the per-class summary percentiles, the "
            "exported snapshot, and the fleet SLO monitor — all three "
            "agree exactly.",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("slo", "str", doc="SLO class"),
            F("tenant", "str", nullable=True, doc="tenant id"),
            F("ttft_ms", "num", nullable=True,
              doc="arrival→first token, across retries/failovers"),
            F("e2e_ms", "num", doc="arrival→last token absorbed"),
            F("tok_ms", "num", nullable=True, doc="mean inter-token ms"),
            F("tokens", "int", doc="tokens generated"),
            F("retries", "int", doc="re-dispatches"),
            F("redispatched", "bool", doc="request moved replicas"),
            F("t_s", "num", doc="router clock seconds"),
        )),
    Schema(
        "fleet_slo_alert",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        doc="Fleet-level SLO burn-rate alert (same machinery and fields "
            "as the per-replica `slo_alert`, namespaced by the router's "
            "`event_prefix=\"fleet_\"`).",
        fields=_SLO_FIELDS),
    Schema(
        "fleet_slo_ok",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        doc="Fleet-level SLO recovery transition.",
        fields=_SLO_FIELDS),
    Schema(
        "fleet_stitch",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        doc="One per run end when `--fleet.trace` is armed (the merged "
            "Perfetto file's path rides `fleet_summary.fleet_trace`).",
        fields=(
            F("stitch_sources", "int",
              doc="router + one per replica epoch"),
            F("stitch_skipped", "int", doc="torn/missing files"),
            F("stitch_balanced", "bool", doc="all spans closed"),
            F("stitch_closed_at_death", "int",
              doc="dead-leg spans the stitcher closed at the redispatch "
                  "instant"),
            F("stitch_error", "str", doc="only when the stitch failed"),
            F("events", "int", doc="events in the merged timeline"),
        )),
    Schema(
        "fleet_decomp",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        doc="Per-request latency decomposition read back from the "
            "merged timeline (`residual_ms` = e2e − sum of parts; "
            "fleetobsbench gates its fraction).",
        fields=(
            F("rid", "str", required=True, doc="request id"),
            F("gens", "list", doc="wire ids, one per dispatch leg"),
            F("e2e_ms", "num", doc="arrival→last token absorbed"),
            F("router_queue_ms", "num", doc="router arrival → dispatch"),
            F("inbox_lag_ms", "num", doc="dispatch write → feed intake"),
            F("replica_queue_ms", "num", doc="intake → admission"),
            F("prefill_ms", "num", doc="admission → first token"),
            F("decode_ms", "num", doc="first → last token"),
            F("absorb_ms", "num",
              doc="replica done → router journal-poll absorb"),
            F("residual_ms", "num", doc="e2e − sum of parts"),
        )),
    Schema(
        "fleet_snapshot",
        section="Fleet observatory (observe/fleet_trace.py, fleet/run.py)",
        open_fields=True,
        doc="The control-plane feed payload, mirrored into the JSONL "
            "whenever the `--fleet.export-path` file is atomically "
            "rewritten. Open record: per-class percentiles (EXACTLY the "
            "summary's numbers — same population, same nearest-rank "
            "percentile) ride along.",
        fields=(
            F("slots", "int", doc="aggregate decode slots"),
            F("slots_live", "int", doc="aggregate live slots"),
            F("queue_depth", "int", doc="router queue depth"),
            F("waiting", "int", doc="requests waiting"),
            F("inflight", "int", doc="requests in flight"),
            F("requests", "int", doc="requests accepted"),
            F("requests_done", "int", doc="requests completed"),
            F("requests_shed", "int", doc="requests shed"),
            F("quarantined", "int", doc="replicas quarantined now"),
            F("deaths", "int", doc="replica deaths so far"),
            F("slo", "dict", doc="SLO state (see `NESTED`)"),
            F("slo_budget_remaining_min", "num", nullable=True,
              doc="min error budget across targets"),
            F("slo_alerting", "list", doc="targets currently alerting"),
            F("replicas", "dict",
              doc="per-replica health map (see `NESTED`)"),
        ),
        patterns=(r"ttft_ms_p\d+(_\w+)?",)),
    # --------------------------------------------------- Run log (stdout)
    Schema(
        "generate", section="Run log (stdout only)", registry=False,
        doc="mode=generate output record.",
        fields=(
            F("step", "int", required=True, doc="checkpoint step sampled"),
            F("prompt", "str", doc="the prompt"),
            F("new_tokens", "list", doc="generated token ids"),
            F("beam_score", "num", doc="beam search score (beam runs)"),
            F("text", "str", doc="decoded text (when a decoder exists)"),
        )),
    Schema(
        "done", section="Run log (stdout only)", registry=False,
        doc="End-of-run stdout rollup (the registry twin is `summary`).",
        fields=(
            F("steps", "int", doc="final global step"),
            F("train_seconds", "num", doc="total train wall"),
            F("compile_seconds", "num", doc="compile wall"),
            F("steps_per_sec", "num", doc="steady-state step rate"),
            F("images_per_sec", "num", doc="steady-state item rate"),
        ),
        patterns=(r"val_\w+",)),
)

# Nested structures consumers traverse inside records and the exported
# snapshot payloads. Keyed by context name; the static consumer pass
# unions these into the readable-field universe, and RECORDS.md renders
# them so pollers know the sub-shapes too.
NESTED: Dict[str, Tuple[Field, ...]] = {
    "slo": (
        F("alerting", "list", doc="targets currently alerting"),
        F("alerts", "int", doc="alert transitions so far"),
        F("burn_fast", "dict", doc="per-target fast-window burn"),
        F("burn_slow", "dict", doc="per-target slow-window burn"),
        F("budget_remaining", "dict", doc="per-target budget remaining"),
        F("threshold_ms", "dict", doc="per-target thresholds"),
        F("targets", "list", doc="declared targets"),
    ),
    "anomaly": (
        F("total", "int", doc="anomaly records so far"),
        F("counts", "dict", doc="per-detector counts"),
        F("active", "list", doc="detectors currently out of envelope"),
        F("anomalies", "int", doc="alias of total in snapshot payloads"),
        F("last", "dict", doc="most recent anomaly record"),
    ),
    "replicas": (
        F("health", "str", doc="`up` | `down` | `quarantined` | …"),
        F("epoch", "int", doc="replica epoch"),
        F("load", "num", doc="occupancy-based load score"),
        F("inflight", "int", doc="requests in flight"),
        F("done", "int", doc="requests completed"),
        F("stale_s", "num", nullable=True, doc="snapshot staleness"),
        F("reason", "str", nullable=True, doc="quarantine reason"),
        F("ckpt_step", "int", nullable=True, doc="model staleness feed"),
        F("tp_width", "int", doc="tensor-parallel width"),
        F("per_device_cache_bytes", "int", doc="per-device cache bytes"),
        F("tune_actions", "int",
          doc="autopilot knob changes on this replica — a replica "
              "self-tuning hard is one whose workload shifted"),
    ),
    # The serve journal's line records (serve/journal.py) — the
    # replay/crash-recovery contract the fleet router also tails.
    "journal-line": (
        F("e", "str", required=True,
          doc="`admit` | `tok` | `done` | `reject`"),
        F("rid", "int", required=True, doc="wire request id"),
        F("prompt", "list", doc="admit: prompt token ids"),
        F("max_new", "int", doc="admit: generation budget"),
        F("eos", "int", doc="admit: eos token id (-1 = none)"),
        F("slo", "str", doc="admit: SLO class"),
        F("tenant", "str", doc="admit: tenant id"),
        F("sess", "str", doc="admit: session id"),
        F("t", "int", doc="tok: the token id"),
        F("s", "num", doc="serve clock seconds of the write"),
    ),
    # serve.journal.fold_record's replay accumulator entries —
    # {rid: {...}} as returned by replay()/read_journal().
    "journal-replay": (
        F("req", "dict", nullable=True,
          doc="admitted request (`prompt`/`max_new`/`eos`)"),
        F("tokens", "list", doc="tokens journaled so far"),
        F("done", "bool", doc="completion record seen"),
        F("reject", "bool", doc="reject record seen"),
        F("last_s", "num", doc="serve clock of the last record"),
    ),
    # The workload file fed to serve/fleet runs (one request per
    # line; fleet/router.submit's intake contract).
    "workload": (
        F("rid", "int", required=True, doc="request id"),
        F("prompt", "list", required=True, doc="prompt token ids"),
        F("max_new", "int", doc="generation budget"),
        F("eos", "int", doc="eos token id (-1 = none)"),
        F("arrival_s", "num", doc="arrival offset from run begin"),
        F("slo", "str", doc="SLO class"),
        F("tenant", "str", doc="tenant id"),
        F("session", "str", doc="conversation id (paged prefix reuse)"),
    ),
    # Perfetto trace-file events (observe/trace.py writers;
    # fleetview/fleet_trace read them back).
    "perfetto": (
        F("traceEvents", "list", doc="top-level event array"),
        F("name", "str", doc="event/metadata name"),
        F("ph", "str", doc="phase (`X`/`b`/`e`/`i`/`M`)"),
        F("ts", "num", doc="microsecond timestamp"),
        F("dur", "num", doc="duration (complete events)"),
        F("pid", "int", doc="process lane"),
        F("tid", "int", doc="thread lane"),
        F("cat", "str", doc="category"),
        F("args", "dict", doc="event payload"),
        F("id", "any", doc="async span id"),
        F("process_death", "bool",
          doc="args flag: span closed by the stitcher at process death"),
    ),
    # observe/regress.py's finding rows — its `--json` output contract
    # and the shape render_table reads back.
    "regress-finding": (
        F("artifact", "str", doc="bench artifact name"),
        F("check", "str", doc="ledger check id"),
        F("verdict", "str",
          doc="`ok` | `improved` | `skip` | `regression`"),
        F("baseline", "any", doc="committed baseline value"),
        F("fresh", "any", doc="freshly-measured value"),
        F("why", "str", doc="human explanation on non-ok verdicts"),
    ),
    # observe/report.py's OWN summary document: the section keys its
    # renderer (and the bench tests) read back from summarize().
    "report": (
        F("hosts", "int", doc="hosts folded into the report"),
        F("records", "int", doc="records folded"),
        F("plan", "dict", doc="Planner section"),
        F("device_time", "list", doc="Device-time section rows"),
        F("device_time_null_records", "int", doc="unattributable rows"),
        F("recovery_counts", "dict", doc="recovery events by kind"),
        F("swap_seconds_total", "num", doc="weight-swap wall total"),
        F("mesh_changes", "int", doc="supervisor mesh changes"),
        F("mesh_change_path", "list", doc="mesh transition chain"),
        F("reshard_seconds_total", "num", doc="reshard wall total"),
        F("fleet", "dict", doc="Fleet section"),
        F("decomposition", "dict", doc="fleet decomposition rollup"),
        F("e2e_ms_p95", "num", doc="fleet e2e p95"),
        F("e2e_ms_mean", "num", doc="decomposition mean e2e"),
        F("router_queue_ms_mean", "num", doc="decomposition component"),
        F("inbox_lag_ms_mean", "num", doc="decomposition component"),
        F("replica_queue_ms_mean", "num", doc="decomposition component"),
        F("prefill_ms_mean", "num", doc="decomposition component"),
        F("decode_ms_mean", "num", doc="decomposition component"),
        F("absorb_ms_mean", "num", doc="decomposition component"),
        F("residual_ms_mean", "num", doc="decomposition residual"),
        F("residual_frac_mean", "num", doc="residual fraction"),
        F("oks", "int", doc="SLO clears"),
        F("alerts_by_target", "dict", doc="SLO alerts per target"),
        F("budget_remaining_min", "num", nullable=True,
          doc="min SLO budget remaining"),
        F("worst_burn_fast", "num", doc="worst fast-window burn"),
        F("snapshot_last", "dict", doc="last metrics_snapshot folded"),
        F("tune", "dict",
          doc="Autopilot section: the run's `tune_summary` rollup plus "
              "the decision records folded per loop"),
        F("by_detector", "dict", doc="anomaly counts per detector"),
        F("postmortem_bundles", "list", doc="bundle paths seen"),
        F("worst_update_ratio", "num", doc="health: worst update ratio"),
        F("worst_update_ratio_step", "int", doc="…and its step"),
        F("grad_norm_first", "num", doc="health: first grad norm"),
        F("grad_norm_last", "num", doc="health: last grad norm"),
    ),
}

_BY_KIND: Dict[str, Schema] = {s.kind: s for s in SCHEMAS}
_TAG_NAMES = frozenset(f.name for f in COMMON_TAGS)

_TYPES = {
    "int": (int,),
    "float": (int, float),
    "num": (int, float),
    "str": (str,),
    "bool": (bool, int),
    "dict": (dict,),
    "list": (list, tuple),
    "any": (object,),
}


def schema_for(kind: str) -> Optional[Schema]:
    return _BY_KIND.get(kind)


def allowed_fields(kind: str) -> Optional[frozenset]:
    """Declared field names + common tags for ``kind`` (None if the
    kind itself is undeclared). Pattern families are NOT expanded here
    — callers match them via :func:`matches_pattern`."""
    s = _BY_KIND.get(kind)
    if s is None:
        return None
    return frozenset(s.field_names()) | _TAG_NAMES


def matches_pattern(kind: str, name: str) -> bool:
    s = _BY_KIND.get(kind)
    if s is None:
        return False
    return any(re.fullmatch(p, name) for p in s.patterns)


def consumer_universe() -> frozenset:
    """Every field name a consumer may read by literal key: all
    declared fields across kinds, the common tags, the nested
    sub-shapes, and the artifact stamp."""
    names = set(_TAG_NAMES) | set(ARTIFACT_STAMP_FIELDS) | {"kind"}
    for s in SCHEMAS:
        names.add(s.kind)  # consumers bucket counts by kind name
        names.update(s.field_names())
    for fields in NESTED.values():
        names.update(f.name for f in fields)
    return frozenset(names)


def consumer_patterns() -> Tuple[str, ...]:
    pats: List[str] = []
    for s in SCHEMAS:
        for p in s.patterns:
            if p not in pats:
                pats.append(p)
    return tuple(pats)


def validate_record(event: str, rec: dict) -> List[str]:
    """Runtime half of the contract (``MetricsRegistry(validate=True)``,
    armed under ``--check``): return a list of violations for one
    assembled record (empty = clean)."""
    s = _BY_KIND.get(event)
    if s is None:
        return [f"undeclared record kind {event!r}"]
    errors: List[str] = []
    by_name = {f.name: f for f in s.fields}
    for f in s.fields:
        if f.required and f.name not in rec and f.name not in _TAG_NAMES:
            errors.append(f"{event}: missing required field {f.name!r}")
    for name, value in rec.items():
        if name in _TAG_NAMES:
            continue
        fld = by_name.get(name)
        if fld is None:
            if matches_pattern(event, name) or s.open_fields:
                continue
            errors.append(f"{event}: undeclared field {name!r}")
            continue
        if value is None:
            if not fld.nullable:
                errors.append(
                    f"{event}: field {name!r} is null but not declared "
                    f"nullable")
            continue
        want = _TYPES.get(fld.type, (object,))
        if not isinstance(value, want) and not hasattr(value, "item"):
            errors.append(
                f"{event}: field {name!r} expected {fld.type}, got "
                f"{type(value).__name__}")
    return errors


# --------------------------------------------------------------------
# RECORDS.md rendering — the doc is generated, never hand-edited.
# --------------------------------------------------------------------

_PREAMBLE = """\
# RECORDS.md — the observe JSONL record schema

> Generated from `observe/schemas.py` — edit the schema registry, then
> run `python -m tensorflow_distributed_tpu.analysis.schema --update`.
> The schema pass (`scripts/lint.sh` / t1) fails on drift.

Every run event flows through ONE registry (`observe/registry.py`) as
a flat JSON object per line. This file enumerates every `event=` kind
the framework emits, with field tables — the contract `observe.report`,
the regress ledger, the calibration fitter, and any external poller
read against. Summarize any stream with
`python -m tensorflow_distributed_tpu.observe.report <metrics.jsonl> [more.jsonl ...]`.

**Common tags on every record** (added by the registry):

| field | meaning |
|---|---|
"""

_CONVENTIONS = """\

Null-field convention: telemetry fields a backend cannot supply are
**explicitly `null`**, never absent — record SHAPE is stable across
platforms. Fields marked *null ok* below follow it; a `null` in any
other declared field is a producer bug (`--check` arms runtime
validation of exactly these tables via `MetricsRegistry(validate=True)`).

Open records (marked below) splat computed rollups, so producers may
add fields beyond the table — but consumers may still only read
DECLARED fields; the one-sided openness keeps the reader contract
statically checkable (`analysis/schema.py`).
"""

_EPILOGUE = """\
## Nested payload shapes

Sub-objects consumers traverse inside `metrics_snapshot` /
`fleet_snapshot` records and the `--observe.export-path` /
`--fleet.export-path` payloads:

"""

_PROVENANCE = """\
## Artifact provenance (not registry events)

Bench artifacts written through `observe.registry.write_jsonl` (and
GRADSYNC's document writer) stamp every record with `git_sha` and
`calibration_id` (`observe.registry.artifact_stamp`) so the regress
ledger (`observe/regress.py`) can name what changed between a fresh
artifact and the committed baseline.
"""


def _md_escape(text: str) -> str:
    return text.replace("|", "\\|")


def _render_field_table(fields: Iterable[Field]) -> List[str]:
    out = ["| field | type | required | null ok | meaning |",
           "|---|---|---|---|---|"]
    for f in fields:
        out.append(
            f"| `{f.name}` | {f.type} | {'yes' if f.required else ''} | "
            f"{'yes' if f.nullable else ''} | {_md_escape(f.doc)} |")
    return out


def render_records_md() -> str:
    lines: List[str] = [_PREAMBLE.rstrip("\n")]
    for f in COMMON_TAGS:
        lines.append(f"| `{f.name}` | {_md_escape(f.doc)} |")
    lines.append(_CONVENTIONS.rstrip("\n"))
    for section, intro in _SECTIONS:
        lines.append("")
        lines.append(f"## {section}")
        if intro:
            lines.append("")
            lines.append(intro)
        for s in SCHEMAS:
            if s.section != section:
                continue
            lines.append("")
            lines.append(f"### `{s.kind}`")
            lines.append("")
            flags = []
            if s.open_fields:
                flags.append("open record")
            if not s.registry:
                flags.append("stdout only")
            if flags:
                lines.append(f"*({', '.join(flags)})* {s.doc}")
            else:
                lines.append(s.doc)
            lines.append("")
            lines.extend(_render_field_table(s.fields))
            if s.patterns:
                pats = ", ".join(f"`{p}`" for p in s.patterns)
                lines.append("")
                lines.append(f"Open field families (regex): {pats}.")
    lines.append("")
    lines.append(_EPILOGUE.rstrip("\n"))
    for name in sorted(NESTED):
        lines.append("")
        lines.append(f"### `{name}`")
        lines.append("")
        lines.extend(_render_field_table(NESTED[name]))
    lines.append("")
    lines.append(_PROVENANCE.rstrip("\n"))
    lines.append("")
    return "\n".join(lines)
