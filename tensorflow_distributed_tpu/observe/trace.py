"""Chrome-trace (Perfetto-compatible) span emitter for HOST phases.

``utils/profiling.py`` captures the XLA device timeline via
``jax.profiler`` — rich, but it needs a live TPU runtime and a
TensorBoard/XPlane toolchain to open. This module is its pure-Python
complement: JSON trace events for the host-side phases the training
loop actually spends wall time in (data wait, dispatch, eval,
checkpoint, preemption drain), written in the Trace Event Format that
chrome://tracing and https://ui.perfetto.dev open directly. It works
even when the TPU tunnel is down — the exact situation where you most
want to see what the host was doing.

Events carry the standard keys: ``ph`` (phase: "X" complete span,
"i" instant, "C" counter, "M" metadata, "b"/"e" async span
begin/end), ``ts``/``dur`` in microseconds, ``name``, ``pid``/
``tid``. The file is written tmp+rename on ``flush()``/``close()``
(idempotent), and flushed periodically so a killed run still leaves
an openable trace.

Async events (``async_begin``/``async_end``) exist for spans that do
NOT nest with the call stack — a serve request's lifecycle interleaves
with every other request's, so its queue/prefill/decode phases are
``b``/``e`` pairs keyed by ``id`` (Perfetto groups same-``id`` events
onto one request track). ``observe/serve_trace.py`` builds the
per-request span trees on top of these primitives.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json

_FLUSH_INTERVAL_S = 5.0  # min seconds between incremental rewrites:
                         # each flush rewrites the whole accumulated
                         # buffer, so an event-count trigger would go
                         # O(n^2) in IO on long runs; a pure time
                         # trigger bounds IO to runtime/5 rewrites AND
                         # keeps a killed run's trace at most ~5s
                         # stale regardless of event rate (close()
                         # always writes everything).


class ChromeTracer:
    """Span/instant/counter recorder -> one Chrome-trace JSON file.

    ``enabled=False`` (or an empty path) makes every method a no-op so
    call sites need no guards. The clock is injectable for tests.
    """

    def __init__(self, path: str = "", pid: int = 0, enabled: bool = True,
                 process_name: str = "", clock=time.perf_counter,
                 max_events: int = 200_000):
        self.path = path
        self.enabled = bool(enabled and path)
        self.pid = pid
        self._clock = clock
        self._t0 = clock()
        self._events: List[Dict[str, Any]] = []
        self._last_flush = clock()
        # Bound host memory (and the rewrite-on-flush cost) like the
        # registry's max_records: past the cap, new events are counted
        # but dropped, and the written trace carries one marker event
        # saying how many. ~3 spans/step, so the default covers ~65k
        # traced steps — far past what a human opens in Perfetto.
        self.max_events = max_events
        self.dropped = 0
        self._ts_offset = 0.0  # microseconds; preload() moves it
        # Async span balance across the max_events cap: counts of
        # RECORDED (appended) vs DROPPED "b" events per (cat, name,
        # id), so async_end can keep the file balanced — an "e" whose
        # "b" made it into the buffer is appended even past the cap
        # (bounded overflow: at most the spans open at the drop
        # point), and an "e" whose "b" was dropped is dropped with it
        # (a stray "e" would unbalance just the same).
        self._open_b: Dict[tuple, int] = {}
        self._dropped_b: Dict[tuple, int] = {}
        if self.enabled and process_name:
            self._events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": process_name}})
            # Wall-clock anchor: this tracer's ts=0 corresponds to
            # wall_ts seconds since the epoch. perf_counter timelines
            # from DIFFERENT processes share no origin; the fleet
            # stitcher (observe/fleet_trace.py) reads each file's
            # FIRST clock_sync to place every source on one absolute
            # axis (refined by the snapshot wall_ts<->mtime offsets).
            # Named-process tracers only — exactly the ones that can
            # become stitch sources.
            self._events.append({
                "ph": "M", "name": "clock_sync", "pid": pid, "tid": 0,
                "args": {"wall_ts": round(time.time(), 6)}})
        # Constructor metadata doesn't eat into the event budget —
        # max_events caps RECORDED work, not the preamble.
        self._preamble = len(self._events)

    def _ts(self) -> float:
        return (self._clock() - self._t0) * 1e6 + self._ts_offset

    def preload(self, events: List[Dict[str, Any]],
                gap_us: float = 1_000.0) -> None:
        """Seed previously-written events (trace RESUME: a restarted
        serve leg continues the dead leg's file) and shift this
        tracer's clock so every new event lands ``gap_us`` after the
        last preloaded one — one file, one monotone timeline across
        process deaths."""
        if not self.enabled or not events:
            return
        self._events = list(events) + self._events
        last = max((float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
                    for e in events), default=0.0)
        self._ts_offset = last + gap_us
        # Unmatched preloaded "b" spans count as OPEN here, so the
        # caller (ServeTracer resume) can close them with async_end.
        for ev in unbalanced_async(events):
            if ev.get("ph") == "b":
                key = self._async_key(ev.get("name"), ev.get("id"),
                                      ev.get("cat"))
                self._open_b[key] = self._open_b.get(key, 0) + 1

    def _tid(self) -> int:
        return threading.get_ident() & 0xFFFF

    def _add(self, event: Dict[str, Any], force: bool = False) -> None:
        if (len(self._events) - self._preamble >= self.max_events
                and not force):
            self.dropped += 1
            return
        self._events.append(event)
        if self._clock() - self._last_flush >= _FLUSH_INTERVAL_S:
            self.flush()

    def _async_key(self, name: str, id: Any, cat: str) -> tuple:
        return (cat, name, str(id))

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host",
             **args: Any) -> Iterator[None]:
        """Complete ("X") event wrapping the with-block."""
        if not self.enabled:
            yield
            return
        start = self._ts()
        try:
            yield
        finally:
            ev: Dict[str, Any] = {
                "ph": "X", "name": name, "cat": cat, "pid": self.pid,
                "tid": self._tid(), "ts": round(start, 3),
                "dur": round(self._ts() - start, 3)}
            if args:
                ev["args"] = args
            self._add(ev)

    def async_begin(self, name: str, id: Any, cat: str = "host",
                    **args: Any) -> None:
        """Open an async ("b") span. ``id`` groups related spans onto
        one track (Perfetto renders same-(cat, id) events together);
        close with :meth:`async_end` using the SAME (name, id, cat).
        Unlike :meth:`span`, begin and end may come from different
        stack frames — the serve scheduler opens a request's queue
        span at arrival and closes it at admission, many iterations
        later."""
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "ph": "b", "name": name, "cat": cat, "pid": self.pid,
            "tid": 0, "id": str(id), "ts": round(self._ts(), 3)}
        if args:
            ev["args"] = args
        key = self._async_key(name, id, cat)
        before = len(self._events)
        self._add(ev)
        tally = (self._open_b if len(self._events) > before
                 else self._dropped_b)
        tally[key] = tally.get(key, 0) + 1

    def async_end(self, name: str, id: Any, cat: str = "host",
                  **args: Any) -> None:
        """Close the async span opened by ``async_begin(name, id,
        cat)``. Balance survives the ``max_events`` cap: an "e" whose
        "b" is in the buffer is recorded even past the cap, one whose
        "b" was dropped is dropped with it."""
        if not self.enabled:
            return
        key = self._async_key(name, id, cat)
        if self._dropped_b.get(key, 0) > 0:
            self._dropped_b[key] -= 1
            if not self._dropped_b[key]:
                del self._dropped_b[key]
            self.dropped += 1
            return
        if self._open_b.get(key, 0) <= 0:
            return          # no matching begin (double-end) — a stray
            #                 "e" would unbalance just like a stray "b"
        self._open_b[key] -= 1
        if not self._open_b[key]:
            del self._open_b[key]
        ev: Dict[str, Any] = {
            "ph": "e", "name": name, "cat": cat, "pid": self.pid,
            "tid": 0, "id": str(id), "ts": round(self._ts(), 3)}
        if args:
            ev["args"] = args
        self._add(ev, force=True)

    def instant(self, name: str, cat: str = "host", **args: Any) -> None:
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "ph": "i", "name": name, "cat": cat, "pid": self.pid,
            "tid": self._tid(), "ts": round(self._ts(), 3), "s": "p"}
        if args:
            ev["args"] = args
        self._add(ev)

    def counter(self, name: str, **values: float) -> None:
        """Counter ("C") track, e.g. ``tracer.counter("mfu", mfu=0.41)``."""
        if not self.enabled:
            return
        self._add({"ph": "C", "name": name, "pid": self.pid, "tid": 0,
                   "ts": round(self._ts(), 3), "args": dict(values)})

    def flush(self) -> None:
        """Write everything recorded so far (tmp+rename, idempotent)."""
        if not self.enabled:
            return
        events = self._events
        if self.dropped:
            events = events + [{
                "ph": "i", "name": f"{self.dropped} events dropped "
                f"(max_events={self.max_events})", "cat": "host",
                "pid": self.pid, "tid": 0,
                "ts": round(self._ts(), 3), "s": "p"}]
        atomic_write_json(self.path, {"traceEvents": events,
                                      "displayTimeUnit": "ms"})
        self._last_flush = self._clock()

    def close(self) -> None:
        self.flush()


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read back a trace file's event list (tests, tooling)."""
    with open(path) as f:
        return json.load(f)["traceEvents"]


def unbalanced_async(events: List[Dict[str, Any]]
                     ) -> List[Dict[str, Any]]:
    """The async "b" events with no matching "e" (same cat/name/id,
    counted multiset-style) — the span-balance check slobench gates
    and :class:`~..serve_trace.ServeTracer` uses to close a dead leg's
    in-flight spans on journal resume. An "e" without a "b" also
    counts (returned with its own ``ph``) — balance means NEITHER."""
    open_spans: Dict[tuple, List[Dict[str, Any]]] = {}
    stray: List[Dict[str, Any]] = []
    for ev in events:
        key = (ev.get("cat"), ev.get("name"), ev.get("id"))
        if ev.get("ph") == "b":
            open_spans.setdefault(key, []).append(ev)
        elif ev.get("ph") == "e":
            if open_spans.get(key):
                open_spans[key].pop()
            else:
                stray.append(ev)
    for evs in open_spans.values():
        stray.extend(evs)
    return stray
