"""Chrome-trace (Perfetto-compatible) span emitter for HOST phases.

``utils/profiling.py`` captures the XLA device timeline via
``jax.profiler`` — rich, but it needs a live TPU runtime and a
TensorBoard/XPlane toolchain to open. This module is its pure-Python
complement: JSON trace events for the host-side phases the training
loop actually spends wall time in (data wait, dispatch, eval,
checkpoint, preemption drain), written in the Trace Event Format that
chrome://tracing and https://ui.perfetto.dev open directly. It works
even when the TPU tunnel is down — the exact situation where you most
want to see what the host was doing.

Events carry the standard keys: ``ph`` (phase: "X" complete span,
"i" instant, "C" counter, "M" metadata), ``ts``/``dur`` in
microseconds, ``name``, ``pid``/``tid``. The file is written
tmp+rename on ``flush()``/``close()`` (idempotent), and flushed
periodically so a killed run still leaves an openable trace.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

_FLUSH_INTERVAL_S = 5.0  # min seconds between incremental rewrites:
                         # each flush rewrites the whole accumulated
                         # buffer, so an event-count trigger would go
                         # O(n^2) in IO on long runs; a pure time
                         # trigger bounds IO to runtime/5 rewrites AND
                         # keeps a killed run's trace at most ~5s
                         # stale regardless of event rate (close()
                         # always writes everything).


class ChromeTracer:
    """Span/instant/counter recorder -> one Chrome-trace JSON file.

    ``enabled=False`` (or an empty path) makes every method a no-op so
    call sites need no guards. The clock is injectable for tests.
    """

    def __init__(self, path: str = "", pid: int = 0, enabled: bool = True,
                 process_name: str = "", clock=time.perf_counter,
                 max_events: int = 200_000):
        self.path = path
        self.enabled = bool(enabled and path)
        self.pid = pid
        self._clock = clock
        self._t0 = clock()
        self._events: List[Dict[str, Any]] = []
        self._last_flush = clock()
        # Bound host memory (and the rewrite-on-flush cost) like the
        # registry's max_records: past the cap, new events are counted
        # but dropped, and the written trace carries one marker event
        # saying how many. ~3 spans/step, so the default covers ~65k
        # traced steps — far past what a human opens in Perfetto.
        self.max_events = max_events
        self.dropped = 0
        if self.enabled and process_name:
            self._events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": process_name}})

    def _ts(self) -> float:
        return (self._clock() - self._t0) * 1e6  # microseconds

    def _tid(self) -> int:
        return threading.get_ident() & 0xFFFF

    def _add(self, event: Dict[str, Any]) -> None:
        if len(self._events) >= self.max_events:
            self.dropped += 1
            return
        self._events.append(event)
        if self._clock() - self._last_flush >= _FLUSH_INTERVAL_S:
            self.flush()

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host",
             **args: Any) -> Iterator[None]:
        """Complete ("X") event wrapping the with-block."""
        if not self.enabled:
            yield
            return
        start = self._ts()
        try:
            yield
        finally:
            ev: Dict[str, Any] = {
                "ph": "X", "name": name, "cat": cat, "pid": self.pid,
                "tid": self._tid(), "ts": round(start, 3),
                "dur": round(self._ts() - start, 3)}
            if args:
                ev["args"] = args
            self._add(ev)

    def instant(self, name: str, cat: str = "host", **args: Any) -> None:
        if not self.enabled:
            return
        ev: Dict[str, Any] = {
            "ph": "i", "name": name, "cat": cat, "pid": self.pid,
            "tid": self._tid(), "ts": round(self._ts(), 3), "s": "p"}
        if args:
            ev["args"] = args
        self._add(ev)

    def counter(self, name: str, **values: float) -> None:
        """Counter ("C") track, e.g. ``tracer.counter("mfu", mfu=0.41)``."""
        if not self.enabled:
            return
        self._add({"ph": "C", "name": name, "pid": self.pid, "tid": 0,
                   "ts": round(self._ts(), 3), "args": dict(values)})

    def flush(self) -> None:
        """Write everything recorded so far (tmp+rename, idempotent)."""
        if not self.enabled:
            return
        events = self._events
        if self.dropped:
            events = events + [{
                "ph": "i", "name": f"{self.dropped} events dropped "
                f"(max_events={self.max_events})", "cat": "host",
                "pid": self.pid, "tid": 0,
                "ts": round(self._ts(), 3), "s": "p"}]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        os.replace(tmp, self.path)
        self._last_flush = self._clock()

    def close(self) -> None:
        self.flush()


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Read back a trace file's event list (tests, tooling)."""
    with open(path) as f:
        return json.load(f)["traceEvents"]
