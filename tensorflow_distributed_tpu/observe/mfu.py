"""Model-FLOPs accounting: tokens/s, imgs/s, TFLOP/s, and MFU.

One home for the FLOPs math the benchmarks used to carry one-off
copies of (benchmarks/lm_perf.py now imports from here). Conventions
(the PaLM/MFU accounting, matmuls only):

- per-token forward = ``2 * N_matmul`` — every matmul parameter is one
  multiply-accumulate per token;
- attention adds ``4 * L * d_model`` per layer forward (QK^T and PV),
  halved for causal because the flash kernel skips masked blocks, and
  window-shaped for sliding-window attention;
- train = 3x forward (the backward pass costs ~2x the forward's
  matmul FLOPs);
- MoE layers count only the ``top_k / num_experts`` fraction of expert
  parameters a token actually routes through — MFU measures useful
  work, not resident weights.

MFU divides achieved model FLOP/s by the chip's bf16 peak. Peaks for
known TPU generations ship in ``PEAK_BF16_FLOPS``; unknown device
kinds (CPU hosts included) report ``None`` rather than a made-up
number — pass an explicit peak (``ObserveConfig.peak_tflops``) to
override.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

# Chip bf16 peaks for MFU. Only kinds we can meet in this environment;
# unknown kinds report mfu as None rather than a made-up number.
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v6 lite": 918e12,   # v6e / Trillium
}

# The reference CNN's fixed architecture (models/cnn.py): MACs per
# image, one forward. Convs count kernel x output-position MACs; the
# dense tail counts its weights.
_MNIST_CNN_MACS = (
    5 * 5 * 1 * 32 * 28 * 28        # conv1, SAME, stride 1
    + 5 * 5 * 32 * 64 * 14 * 14     # conv2 after 2x2 pool
    + 3136 * 1024                   # dense 7*7*64 -> 1024
    + 1024 * 10                     # logits
)


def device_peak_flops(device=None) -> Optional[float]:
    """Per-device bf16 peak by device kind; None when unknown."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    return PEAK_BF16_FLOPS.get(dev.device_kind)


def matmul_params(params, moe_experts: int = 0, moe_top_k: int = 2
                  ) -> float:
    """Parameters that participate in matmuls, weighted by how often a
    token uses them: every kernel of ndim >= 2 except embedding tables
    (lookups, not matmuls); MoE expert kernels (the stacked ndim >= 3
    ``wi``/``wo`` tensors inside MoeMlp) count the routed
    ``top_k / num_experts`` fraction only."""
    import jax

    total = 0.0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = jax.tree_util.keystr(path)
        if leaf.ndim < 2 or "emb" in name:
            continue
        if (moe_experts > 0 and leaf.ndim >= 3
                and "moe" in name.lower()):
            total += leaf.size * min(moe_top_k, moe_experts) / moe_experts
        else:
            total += leaf.size
    return total


def attn_flops_per_token_fwd(cfg, seq_len: Optional[int] = None) -> float:
    """QK^T + PV FLOPs per token, one forward: 4 * d_model * (average
    attended length) per layer. Full bidirectional attends L; causal
    ~L/2 (the kernel skips masked blocks); sliding-window attends
    min(W, pos+1) — the windowed kernel skips out-of-band blocks, so
    MFU keeps counting only useful work. ``seq_len`` overrides
    ``cfg.max_len`` when the data stream trains shorter windows than
    the model's position budget."""
    L = seq_len or cfg.max_len
    per_len = 4.0 * cfg.d_model * cfg.n_layers
    if not cfg.causal:
        return per_len * L
    W = getattr(cfg, "attn_window", 0) or 0
    if W and W < L:
        avg = (W * (W + 1) / 2.0 + (L - W) * W) / L
    else:
        avg = L / 2.0
    return per_len * avg


def flops_per_token(params, cfg, seq_len: Optional[int] = None) -> float:
    """Transformer-family model FLOPs per trained token, fwd + bwd."""
    n = matmul_params(params,
                      moe_experts=getattr(cfg, "moe_experts", 0),
                      moe_top_k=getattr(cfg, "moe_top_k", 2))
    return 3.0 * (2.0 * n + attn_flops_per_token_fwd(cfg, seq_len))


def pipelined_hw_flops_per_token(params, cfg,
                                 seq_len: Optional[int] = None) -> float:
    """HARDWARE FLOPs per token for the 1F1B-recompute schedule: model
    FLOPs charge 3x-forward, but recompute EXECUTES 4x-forward for the
    block stack (each backward tick re-runs the stage forward from the
    stashed input). Reported alongside model MFU so the schedule's
    remat trade isn't misread as MXU inefficiency."""
    blocks_n = matmul_params(params["blocks"],
                             moe_experts=getattr(cfg, "moe_experts", 0),
                             moe_top_k=getattr(cfg, "moe_top_k", 2))
    return (flops_per_token(params, cfg, seq_len)
            + 2.0 * blocks_n + attn_flops_per_token_fwd(cfg, seq_len))


_TRANSFORMER_FAMILIES = ("bert_mlm", "gpt_lm", "moe_lm", "pipelined_lm")


def flops_per_item(model_name: str, params=None, model_cfg=None,
                   seq_len: Optional[int] = None
                   ) -> Tuple[Optional[float], str]:
    """(train FLOPs per item, item unit) for a model family.

    Unit is "token" for the LM families, "image" for vision. Families
    without an estimator (the ResNets — conv FLOPs depend on spatial
    shapes this module doesn't model) return ``(None, unit)``:
    throughput still reports, MFU is omitted rather than invented.
    """
    if model_name == "mnist_cnn":
        return 3.0 * 2.0 * _MNIST_CNN_MACS, "image"
    if model_name in _TRANSFORMER_FAMILIES:
        if params is None or model_cfg is None:
            return None, "token"
        return flops_per_token(params, model_cfg, seq_len), "token"
    return None, "image"


class ThroughputAccountant:
    """Turns (items, seconds) windows into items/s, TFLOP/s, and MFU.

    ``peak_flops_total`` is the AGGREGATE peak across all devices in
    the job (per-device peak x device count); None omits MFU.
    ``hw_flops_per_item`` (optional) adds a parallel hardware-
    utilization number (pipelined recompute executes more FLOPs than
    the model math credits).
    """

    def __init__(self, flops_per_item: Optional[float] = None,
                 unit: str = "item",
                 peak_flops_total: Optional[float] = None,
                 hw_flops_per_item: Optional[float] = None):
        self.flops_per_item = flops_per_item
        self.unit = unit
        self.peak_flops_total = peak_flops_total or None
        self.hw_flops_per_item = hw_flops_per_item

    def rates(self, items: float, seconds: float) -> Dict[str, Any]:
        if seconds <= 0 or items <= 0:
            return {}
        per_sec = items / seconds
        out: Dict[str, Any] = {
            f"{self.unit}s_per_sec": round(per_sec, 2)}
        if self.flops_per_item:
            flops_s = per_sec * self.flops_per_item
            out["model_tflops"] = round(flops_s / 1e12, 4)
            if self.peak_flops_total:
                out["mfu"] = round(flops_s / self.peak_flops_total, 4)
                if self.hw_flops_per_item:
                    out["hw_mfu"] = round(
                        per_sec * self.hw_flops_per_item
                        / self.peak_flops_total, 4)
        return out
