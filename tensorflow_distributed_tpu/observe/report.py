"""Summarize metrics JSONLs: ``python -m tensorflow_distributed_tpu.observe.report <metrics.jsonl> [more.jsonl ...]``.

Regenerates the headline numbers a BENCH artifact wants — p50/p95 step
time, mean throughput and MFU, goodput % — from the raw JSONL the
:mod:`observe.registry` JSONL sink wrote, so bench records can always
be re-derived from (and audited against) the primary artifact.

Multiple paths merge into ONE report (each process of a multi-host
run writes its own host-tagged stream — registry.host_tags stamps
``process_index`` on every record); when records from more than one
host are present, a per-host section breaks the headline stats down
by origin.

``--json`` prints one machine-readable JSON object instead of the
human table.
"""

from __future__ import annotations

import argparse
import json
import sys

from tensorflow_distributed_tpu.observe import device as _device
from typing import Any, Dict, List


def load_records(path: str) -> List[Dict[str, Any]]:
    """Parse a metrics JSONL, SKIPPING malformed lines.

    A crashed or killed run leaves exactly the file this report exists
    for — and possibly a truncated final line (the sink flushes per
    record, but the OS can still cut a write mid-line at SIGKILL, and
    NFS appends can interleave). Raising on one bad line would make the
    report unavailable precisely when it matters: count-and-skip, note
    it on stderr, summarize the rest."""
    records = []
    bad, first_bad = 0, 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                bad += 1
                first_bad = first_bad or i
    if bad:
        print(f"observe.report: {path}: skipped {bad} malformed "
              f"line(s) (first at line {first_bad}) — partial write "
              f"from a crashed run?", file=sys.stderr)
    return records


def _mean(values: List[float]) -> float:
    return sum(values) / len(values)


# THE percentile formula (observe/slo.py, stdlib-only): the live
# snapshot and this post-run report must agree exactly — slobench
# gates that equality, so there is ONE definition.
from tensorflow_distributed_tpu.observe.slo import (  # noqa: E402
    percentile as _percentile)


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate step/summary events into the report dict."""
    steps = [r for r in records if r.get("event") == "step"]
    summaries = [r for r in records if r.get("event") == "summary"]
    out: Dict[str, Any] = {"records": len(records),
                           "step_records": len(steps)}
    # Serve-mode records (serve/scheduler.py): per-request
    # serve_request rows + one serve_summary — reported alongside the
    # training summary so one JSONL tells the whole story.
    serve_reqs = [r for r in records if r.get("event") == "serve_request"]
    serve_sums = [r for r in records if r.get("event") == "serve_summary"]
    if serve_reqs:
        out["serve_requests"] = len(serve_reqs)
        ttfts = sorted(float(r["ttft_ms"]) for r in serve_reqs
                       if isinstance(r.get("ttft_ms"), (int, float)))
        if ttfts:
            out["serve_ttft_ms_p50"] = round(_percentile(ttfts, 50), 3)
            out["serve_ttft_ms_p95"] = round(_percentile(ttfts, 95), 3)
            out["serve_ttft_ms_p99"] = round(_percentile(ttfts, 99), 3)
        # Requests whose arrival->first-token window overlapped a
        # recovery event (slot quarantine / weight swap) — the
        # availability population FIREBENCH's p99-TTFT-during-recovery
        # gate reads.
        rec_ttfts = sorted(
            float(r["ttft_ms"]) for r in serve_reqs
            if r.get("recovery_window")
            and isinstance(r.get("ttft_ms"), (int, float)))
        if rec_ttfts:
            out["serve_recovery_requests"] = len(rec_ttfts)
            out["serve_ttft_ms_p99_recovery"] = round(
                _percentile(rec_ttfts, 99), 3)
        toks = [float(r["tok_ms"]) for r in serve_reqs
                if isinstance(r.get("tok_ms"), (int, float))]
        if toks:
            out["serve_tok_ms_mean"] = round(_mean(toks), 4)
        # Per-SLO-class TTFT p95 (serve/scheduler.py policy="slo"
        # tags every serve_request with its class): the split the SLO
        # scheduler exists to move — only emitted when a non-default
        # class actually appears, so plain FIFO reports are unchanged.
        by_class: Dict[str, List[float]] = {}
        for r in serve_reqs:
            if isinstance(r.get("ttft_ms"), (int, float)):
                by_class.setdefault(str(r.get("slo", "standard")),
                                    []).append(float(r["ttft_ms"]))
        if len(by_class) > 1 or set(by_class) - {"standard"}:
            for cls, vals in sorted(by_class.items()):
                out[f"serve_ttft_ms_p95_{cls}"] = round(
                    _percentile(sorted(vals), 95), 3)
    if serve_sums:
        final = serve_sums[-1]
        for key in ("tokens_per_sec", "mean_slot_occupancy",
                    "total_new_tokens", "prefill_compiles", "retries",
                    "swaps", "swap_seconds", "seed", "trace",
                    "policy", "preemptions", "spec_tokens",
                    "verify_steps", "accept_rate", "tune_actions",
                    "spec_fallback_slots", "slo_alerts",
                    "slo_budget_remaining_min", "slo_targets",
                    # Paged KV + prefix reuse (serve/paging): pool
                    # occupancy, hit rate, evictions — present only
                    # when the run served paged (plain reports stay
                    # shape-stable).
                    "prefix_hit_rate", "prefix_hits",
                    "pool_occupancy", "pages_peak",
                    "slot_pages_peak", "page_evictions",
                    "cow_copies", "sessions"):
            if key in final:
                out[f"serve_{key}"] = final[key]
    # Live SLO monitor events (observe/slo.py): alert/clear
    # transitions per target plus the last reported budget state —
    # the burn-rate story beside the latency percentiles above.
    slo_events = [r for r in records
                  if r.get("event") in ("slo_alert", "slo_ok")]
    if slo_events:
        by_target: Dict[str, Dict[str, Any]] = {}
        for r in slo_events:
            entry = by_target.setdefault(str(r.get("target", "?")),
                                         {"alerts": 0, "clears": 0})
            if r["event"] == "slo_alert":
                entry["alerts"] += 1
                entry["worst_burn_fast"] = max(
                    entry.get("worst_burn_fast", 0.0),
                    float(r.get("burn_fast", 0.0)))
            else:
                entry["clears"] += 1
            if isinstance(r.get("budget_remaining"), (int, float)):
                entry["budget_remaining"] = r["budget_remaining"]
        out["slo"] = dict(sorted(by_target.items()))
    # Rolling metrics snapshots (scheduler.metrics_snapshot, dumped on
    # --observe.export-every): count + the final point-in-time view.
    # The last snapshot is forced at run end over every completion, so
    # its per-class p95s must AGREE with the serve_request-derived
    # numbers above (slobench gates the equality).
    snapshots = [r for r in records
                 if r.get("event") == "metrics_snapshot"]
    if snapshots:
        out["snapshots"] = len(snapshots)
        last = snapshots[-1]
        keep = ("t_s", "decode_steps", "requests_done", "queue_depth",
                "slot_occupancy", "tokens_per_sec",
                "tokens_per_sec_window", "accept_rate",
                "accept_rate_window", "spec_tokens", "tune_actions",
                "retries", "preemptions", "swaps")
        entry = {k: last[k] for k in keep if k in last}
        for k in sorted(last):
            if k.startswith("ttft_ms_p"):
                entry[k] = last[k]
        out["snapshot_last"] = entry
    # Autopilot decision ledger (observe/autopilot.py): the run-end
    # tune_summary rollup plus the decision records folded per loop —
    # a quiet well-tuned run shows actions=0 here.
    tunes = [r for r in records if r.get("event") == "tune"]
    tune_sums = [r for r in records
                 if r.get("event") == "tune_summary"]
    if tunes or tune_sums:
        tentry: Dict[str, Any] = {}
        if tune_sums:
            tfin = tune_sums[-1]
            for k in ("evals", "actions", "advisories", "suppressed",
                      "by_knob", "quiet"):
                if k in tfin:
                    tentry[k] = tfin[k]
        by_loop: Dict[str, int] = {}
        for r in tunes:
            lp = str(r.get("loop", "?"))
            by_loop[lp] = by_loop.get(lp, 0) + 1
        if by_loop:
            tentry["decisions_by_loop"] = dict(sorted(
                by_loop.items()))
        out["tune"] = tentry
    # SLO preempt-and-requeue events (policy, not failure — reported
    # apart from the Recovery section).
    preempts = [r for r in records if r.get("event") == "preempt"]
    if preempts:
        out["serve_preempt_events"] = len(preempts)
    # Paged-KV events (serve/paging): per-admission prefix hits and
    # pressure evictions (RECORDS.md: prefix_hit / page_evict).
    hits = [r for r in records if r.get("event") == "prefix_hit"]
    if hits:
        out["serve_prefix_hit_events"] = len(hits)
        out["serve_prefix_hit_tokens"] = sum(
            int(r.get("hit_tokens", 0)) for r in hits)
    evicts = [r for r in records if r.get("event") == "page_evict"]
    if evicts:
        out["serve_page_evict_events"] = len(evicts)
        out["serve_pages_evicted"] = sum(
            int(r.get("evicted", 0)) for r in evicts)
    if steps:
        out["last_step"] = max(int(r.get("step", 0)) for r in steps)
        # The freshest rolling-window stats (each step record carries
        # the window's p50/p95 at that point; the last one covers the
        # run's tail — the steady state).
        for key in ("step_ms_p50", "step_ms_p95", "data_ms",
                    "dispatch_ms", "device_ms", "comm_ms_est",
                    "comm_exposed_ms_est"):
            vals = [r[key] for r in steps if key in r]
            if vals:
                out[key] = round(vals[-1], 3)
        for key in ("tokens_per_sec", "images_per_sec", "items_per_sec",
                    "model_tflops", "mfu", "hw_mfu"):
            vals = [float(r[key]) for r in steps
                    if isinstance(r.get(key), (int, float))]
            if vals:
                out[f"mean_{key}"] = round(_mean(vals), 4)
        losses = [float(r["loss"]) for r in steps
                  if isinstance(r.get("loss"), (int, float))]
        if losses:
            out["first_loss"], out["last_loss"] = (round(losses[0], 5),
                                                   round(losses[-1], 5))
    if summaries:
        final = summaries[-1]
        for key, val in final.items():
            if key.endswith("_seconds") or key == "goodput":
                out[key] = val
    # Recovery events (resilience/ + serve fire paths): count by kind
    # plus the rewind/swap time totals, so ONE report shows traffic
    # and faults together.
    recoveries = [r for r in records if r.get("event") == "recovery"]
    if recoveries:
        counts: Dict[str, int] = {}
        for r in recoveries:
            kind = str(r.get("kind", "?"))
            counts[kind] = counts.get(kind, 0) + 1
        out["recovery_counts"] = dict(sorted(counts.items()))
        swap_s = sum(float(r.get("seconds", 0.0)) for r in recoveries
                     if r.get("kind") == "weight_swap"
                     and isinstance(r.get("seconds"), (int, float)))
        if swap_s:
            out["swap_seconds_total"] = round(swap_s, 4)
        # Elastic restarts: mesh_change (the supervisor's resize
        # decision) and reshard_restore (the loop's resharded resume,
        # which carries the resize window's wall time). A supervised
        # resize emits both — the transition path prefers the
        # supervisor's records, the seconds come from the restores.
        mesh_moves = [r for r in recoveries
                      if r.get("kind") in ("mesh_change",
                                           "reshard_restore")]
        if mesh_moves:
            def _fmt(shape):
                if not isinstance(shape, dict):
                    return "?"
                parts = [f"{k}={v}" for k, v in shape.items()
                         if v != 1]
                return ",".join(parts) if parts else "single-device"

            changes = [r for r in mesh_moves
                       if r.get("kind") == "mesh_change"] or mesh_moves
            out["mesh_changes"] = len(changes)
            out["mesh_change_path"] = ", ".join(
                f"{_fmt(r.get('from_mesh'))} -> {_fmt(r.get('to_mesh'))}"
                for r in changes)
            reshard_s = sum(
                float(r["seconds"]) for r in mesh_moves
                if r.get("kind") == "reshard_restore"
                and isinstance(r.get("seconds"), (int, float)))
            if reshard_s:
                out["reshard_seconds_total"] = round(reshard_s, 4)
    # Fleet serving (fleet/router.py + fleet/controller.py records):
    # the front-end's fleet_summary headline (goodput inputs,
    # staleness, shed counts, the dispatch-retry histogram) plus a
    # per-replica breakdown assembled from the dispatch / lifecycle /
    # swap event streams — rendered beside the Recovery section.
    fl_sums = [r for r in records if r.get("event") == "fleet_summary"]
    fl_disp = [r for r in records
               if r.get("event") == "fleet_dispatch"]
    fl_shed = [r for r in records if r.get("event") == "fleet_shed"]
    fl_rep = [r for r in records if r.get("event") == "fleet_replica"]
    fl_swap = [r for r in records if r.get("event") == "fleet_swap"]
    if fl_sums or fl_disp or fl_rep:
        entry: Dict[str, Any] = {}
        if fl_sums:
            final = fl_sums[-1]
            for key in ("requests", "requests_done", "requests_shed",
                        "requests_lost", "dispatches", "redispatches",
                        "dispatch_retry_hist", "quarantines",
                        "rejoins", "deaths", "restarts",
                        "rolling_swaps", "staleness_max_steps",
                        "tokens_per_sec", "wall_s", "ttft_ms_p50",
                        "ttft_ms_p95", "ttft_ms_p99",
                        "recovery_requests", "ttft_ms_p99_recovery",
                        "shed_by_class", "shed_reasons"):
                if key in final:
                    entry[key] = final[key]
        if "dispatch_retry_hist" not in entry and fl_disp:
            # No summary landed (crashed front-end): re-derive the
            # histogram from the dispatch records' retry tags.
            worst: Dict[Any, int] = {}
            for r in fl_disp:
                rid = r.get("rid")
                worst[rid] = max(worst.get(rid, 0),
                                 int(r.get("retry", 0)))
            hist: Dict[str, int] = {}
            for n in worst.values():
                hist[str(n)] = hist.get(str(n), 0) + 1
            entry["dispatch_retry_hist"] = dict(
                sorted(hist.items(), key=lambda kv: int(kv[0])))
        if fl_shed:
            entry["shed_events"] = len(fl_shed)
        replicas: Dict[str, Dict[str, Any]] = {}

        def _rep_entry(name: Any) -> Dict[str, Any]:
            return replicas.setdefault(str(name), {})

        for r in fl_disp:
            e = _rep_entry(r.get("replica", "?"))
            e["dispatches"] = e.get("dispatches", 0) + 1
        for r in fl_rep:
            e = _rep_entry(r.get("replica", "?"))
            state = str(r.get("state", "?"))
            e[state] = e.get(state, 0) + 1
        for r in fl_swap:
            e = _rep_entry(r.get("replica", "?"))
            e["swaps"] = e.get("swaps", 0) + 1
        if replicas:
            entry["replicas"] = dict(sorted(replicas.items()))
        # Fleet observatory (PR 16): client-perceived end-to-end
        # latency per class from the router's fleet_request records —
        # the SAME population + nearest-rank percentile the router's
        # summary and the --fleet.export-path snapshot use, so all
        # three agree exactly (snapshot == report, fleet level).
        fl_req = [r for r in records
                  if r.get("event") == "fleet_request"]
        if fl_req:
            entry["e2e_requests"] = len(fl_req)
            by_cls: Dict[str, List[float]] = {}
            for r in fl_req:
                if isinstance(r.get("ttft_ms"), (int, float)):
                    by_cls.setdefault(
                        str(r.get("slo", "standard")), []).append(
                        float(r["ttft_ms"]))
            for cls, vals in sorted(by_cls.items()):
                vals.sort()
                entry[f"ttft_ms_p50_{cls}"] = round(
                    _percentile(vals, 50), 3)
                entry[f"ttft_ms_p95_{cls}"] = round(
                    _percentile(vals, 95), 3)
            e2es = sorted(float(r["e2e_ms"]) for r in fl_req
                          if isinstance(r.get("e2e_ms"), (int, float)))
            if e2es:
                entry["e2e_ms_p95"] = round(_percentile(e2es, 95), 3)
        # Fleet SLO transitions (the router-level monitor): alert /
        # all-clear counts per target + the final budget floor.
        fl_alerts = [r for r in records
                     if r.get("event") == "fleet_slo_alert"]
        fl_oks = [r for r in records
                  if r.get("event") == "fleet_slo_ok"]
        if fl_alerts or fl_oks:
            slo_entry: Dict[str, Any] = {
                "alerts": len(fl_alerts), "oks": len(fl_oks)}
            by_tgt: Dict[str, int] = {}
            for r in fl_alerts:
                t = str(r.get("target", "?"))
                by_tgt[t] = by_tgt.get(t, 0) + 1
            if by_tgt:
                slo_entry["alerts_by_target"] = dict(
                    sorted(by_tgt.items()))
            budgets = [r.get("budget_remaining")
                       for r in fl_alerts + fl_oks
                       if isinstance(r.get("budget_remaining"),
                                     (int, float))]
            if budgets:
                slo_entry["budget_remaining_min"] = min(budgets)
            entry["slo"] = slo_entry
        # Per-dispatch latency decomposition (stitched-trace derived):
        # mean component split + the residual fraction the bench
        # gates.
        fl_dec = [r for r in records
                  if r.get("event") == "fleet_decomp"]
        if fl_dec:
            comps = ("e2e_ms", "router_queue_ms", "inbox_lag_ms",
                     "replica_queue_ms", "prefill_ms", "decode_ms",
                     "absorb_ms", "residual_ms")
            dec_entry: Dict[str, Any] = {"requests": len(fl_dec)}
            for key in comps:
                vals = [float(r.get(key, 0.0)) for r in fl_dec]
                dec_entry[f"{key}_mean"] = round(
                    sum(vals) / len(vals), 3)
            fracs = [abs(float(r.get("residual_ms", 0.0)))
                     / float(r["e2e_ms"]) for r in fl_dec
                     if float(r.get("e2e_ms", 0.0)) > 0]
            if fracs:
                dec_entry["residual_frac_mean"] = round(
                    sum(fracs) / len(fracs), 4)
            entry["decomposition"] = dec_entry
        fl_snaps = [r for r in records
                    if r.get("event") == "fleet_snapshot"]
        if fl_snaps:
            entry["snapshots"] = len(fl_snaps)
        out["fleet"] = entry
    # Incident observatory (observe/anomaly.py "anomaly" records +
    # observe/flightrec.py "postmortem" records): per-detector counts,
    # the last anomaly, and any postmortem bundle the run dumped.
    anoms = [r for r in records if r.get("event") == "anomaly"]
    if anoms:
        by_det: Dict[str, int] = {}
        for r in anoms:
            det = str(r.get("detector", "?"))
            by_det[det] = by_det.get(det, 0) + 1
        last = anoms[-1]
        out["anomalies"] = {
            "count": len(anoms),
            "by_detector": dict(sorted(by_det.items())),
            "last": {k: last[k] for k in
                     ("detector", "severity", "step") if k in last},
        }
    posts = [r for r in records if r.get("event") == "postmortem"]
    if posts:
        out["postmortem_bundles"] = [
            r.get("bundle") for r in posts if r.get("bundle")]
    # Auto-layout planner (--plan auto, analysis/planner): the chosen
    # mesh/strategy and its predicted step time, reported beside the
    # MEASURED step time when the run got far enough to have one —
    # the audit trail for "why is this run on this mesh".
    plans = [r for r in records if r.get("event") == "plan"]
    if plans:
        p = plans[-1]
        entry: Dict[str, Any] = {
            "family": p.get("family"),
            "mesh": p.get("mesh"),
            "strategy": p.get("strategy"),
            "partition": p.get("partition"),
            "predicted_step_ms": p.get("predicted_step_ms"),
            "predicted_peak_hbm_bytes": p.get(
                "predicted_peak_hbm_bytes"),
            "candidates": p.get("candidates"),
            "feasible": p.get("feasible"),
            "infeasible": p.get("infeasible"),
        }
        if p.get("calibration_id"):
            entry["calibration_id"] = p["calibration_id"]
        if "step_ms_p50" in out:
            entry["measured_step_ms_p50"] = out["step_ms_p50"]
        # Predicted -> measured drift the loop emitted at run end
        # (train/loop.py "plan_drift"): the cost model's error on this
        # very run, the signal a calibration refit consumes.
        drifts = [r for r in records if r.get("event") == "plan_drift"]
        if drifts:
            d = drifts[-1]
            entry["drift_ratio"] = d.get("drift_ratio")
            entry["measured_step_ms_p50"] = d.get(
                "measured_step_ms_p50", entry.get(
                    "measured_step_ms_p50"))
        out["plan"] = entry
    # Device-time attribution (observe/xprof.py "device_time"
    # records): measured device wall per program beside its roofline
    # prediction — the ground-truth layer. Latest record per program
    # (or per module for unmatched ones); explicit-null parses are
    # counted, not rendered as rows.
    dts = [r for r in records if r.get("event") == "device_time"]
    if dts:
        by_prog: Dict[str, Dict[str, Any]] = {}
        nulls = 0
        for r in dts:
            key = r.get("program") or r.get("module")
            if key is None or r.get("device_ms") is None:
                nulls += 1
                continue
            by_prog[str(key)] = r
        entries = []
        for key, r in sorted(by_prog.items(),
                             key=lambda kv: -(kv[1].get("device_ms")
                                              or 0)):
            entries.append({k: r.get(k) for k in (
                "program", "module", "device_ms",
                "device_ms_per_call", "calls", "predicted_ms_per_call",
                "collective_ms", "exposed_collective_ms", "coarse",
                "calibration_id") if r.get(k) is not None})
        out["device_time"] = entries
        if nulls:
            out["device_time_null_records"] = nulls
    # Compiled-program registry (observe/device.py "compile" records):
    # latest record per program — name, flops, peak-HBM estimate,
    # compile seconds — the device-side cost/memory inventory.
    compiles = [r for r in records if r.get("event") == "compile"]
    if compiles:
        by_name: Dict[str, Dict[str, Any]] = {}
        for r in compiles:
            if r.get("program"):
                by_name[r["program"]] = r
        out["programs"] = [
            {"program": name,
             "flops": rec.get("flops"),
             "peak_hbm_bytes": rec.get("peak_hbm_bytes"),
             "donated_bytes": rec.get("donated_bytes"),
             "compile_s": rec.get("compile_s")}
            for name, rec in sorted(by_name.items())]
        budgets = [r for r in records if r.get("event") == "hbm_budget"]
        if budgets and "peak_hbm_bytes_sum" in budgets[-1]:
            out["peak_hbm_bytes_sum"] = budgets[-1]["peak_hbm_bytes_sum"]
    # Per-module health records (observe/health.py): worst update
    # ratio over the run plus first->last grad-norm trend per module.
    healths = [r for r in records if r.get("event") == "health"]
    if healths:
        by_module: Dict[str, List[Dict[str, Any]]] = {}
        for r in healths:
            if r.get("module"):
                by_module.setdefault(r["module"], []).append(r)
        health_out: Dict[str, Dict[str, Any]] = {}
        for module, recs in sorted(by_module.items()):
            entry: Dict[str, Any] = {"records": len(recs)}
            ratios = [(float(r["update_ratio"]), int(r.get("step", 0)))
                      for r in recs
                      if isinstance(r.get("update_ratio"), (int, float))]
            if ratios:
                worst, at = max(ratios)
                entry["worst_update_ratio"] = round(worst, 8)
                entry["worst_update_ratio_step"] = at
            gnorms = [float(r["grad_norm"]) for r in recs
                      if isinstance(r.get("grad_norm"), (int, float))]
            if gnorms:
                entry["grad_norm_first"] = round(gnorms[0], 8)
                entry["grad_norm_last"] = round(gnorms[-1], 8)
            for key in ("param_rms", "act_rms"):
                vals = [float(r[key]) for r in recs
                        if isinstance(r.get(key), (int, float))]
                if vals:
                    entry[f"{key}_last"] = round(vals[-1], 8)
            health_out[module] = entry
        out["health"] = health_out
    # Per-host breakdown, only when records from more than one host
    # tag are merged (multi-host runs: one JSONL per process, each
    # stamped with its process_index by registry.host_tags).
    hosts = sorted({r.get("process_index") for r in records
                    if r.get("process_index") is not None})
    if len(hosts) > 1:
        per_host: Dict[str, Dict[str, Any]] = {}
        for host in hosts:
            recs = [r for r in records
                    if r.get("process_index") == host]
            hsteps = [r for r in recs if r.get("event") == "step"]
            entry = {"records": len(recs)}
            if hsteps:
                entry["step_records"] = len(hsteps)
                entry["last_step"] = max(int(r.get("step", 0))
                                         for r in hsteps)
                p50s = [r["step_ms_p50"] for r in hsteps
                        if "step_ms_p50" in r]
                if p50s:
                    entry["step_ms_p50"] = round(p50s[-1], 3)
            hserve = [r for r in recs
                      if r.get("event") == "serve_request"]
            if hserve:
                entry["serve_requests"] = len(hserve)
            per_host[str(host)] = entry
        out["hosts"] = per_host
    return out





def render(summary: Dict[str, Any]) -> str:
    lines = ["observe.report"]
    order = ("records", "step_records", "last_step", "step_ms_p50",
             "step_ms_p95", "data_ms", "dispatch_ms", "device_ms",
             "mean_tokens_per_sec", "mean_images_per_sec",
             "mean_items_per_sec", "mean_model_tflops", "mean_mfu",
             "mean_hw_mfu", "first_loss", "last_loss", "goodput",
             "serve_requests", "serve_ttft_ms_p50", "serve_ttft_ms_p95",
             "serve_ttft_ms_p99", "serve_recovery_requests",
             "serve_ttft_ms_p99_recovery",
             "serve_tok_ms_mean", "serve_tokens_per_sec",
             "serve_mean_slot_occupancy", "serve_total_new_tokens",
             "serve_prefill_compiles", "serve_retries", "serve_swaps",
             "serve_swap_seconds", "serve_policy", "serve_preemptions",
             "serve_preempt_events", "serve_spec_tokens",
             "serve_verify_steps", "serve_accept_rate",
             "serve_spec_fallback_slots", "serve_tune_actions",
             "serve_slo_alerts",
             "serve_slo_budget_remaining_min", "serve_slo_targets",
             "serve_seed", "serve_trace", "snapshots")
    # plan/programs/health/recovery/slo render as their own sections
    # below; peak_hbm_bytes_sum renders as the Programs TOTAL row.
    sections = ("plan", "programs", "health", "peak_hbm_bytes_sum",
                "recovery_counts", "swap_seconds_total",
                "mesh_changes", "mesh_change_path",
                "reshard_seconds_total", "slo", "snapshot_last",
                "tune", "fleet", "anomalies", "postmortem_bundles",
                "device_time", "device_time_null_records", "hosts",
                # rendered inside the Device time section, not the
                # generic stats list (one print per number).
                "comm_ms_est", "comm_exposed_ms_est")
    for key in order:
        if key in summary:
            lines.append(f"  {key:<22} {summary[key]}")
    extras = [k for k in sorted(summary)
              if k not in order and k not in sections]
    for key in extras:
        lines.append(f"  {key:<22} {summary[key]}")
    if "plan" in summary:
        # Lazy, stdlib-only import: THE planner mesh formatter.
        from tensorflow_distributed_tpu.analysis.planner.candidates \
            import format_mesh
        p = summary["plan"]
        mesh = p.get("mesh") or {}
        mesh_s = format_mesh(mesh) if isinstance(mesh, dict) else "?"
        lines.append("Plan")
        lines.append(f"  {'chosen':<28} {mesh_s} "
                     f"[{p.get('strategy')}] "
                     f"partition={p.get('partition')}")
        pred = p.get("predicted_step_ms")
        meas = p.get("measured_step_ms_p50")
        step_line = (f"predicted={pred} ms"
                     if pred is not None else "predicted=-")
        if meas is not None:
            step_line += f" measured_p50={meas} ms"
        lines.append(f"  {'step_time':<28} {step_line}")
        lines.append(
            f"  {'peak_hbm':<28} "
            f"{_device.human_bytes(p.get('predicted_peak_hbm_bytes'))}")
        lines.append(f"  {'candidates':<28} {p.get('candidates')} "
                     f"({p.get('feasible')} feasible, "
                     f"{p.get('infeasible')} infeasible)")
        if p.get("drift_ratio") is not None:
            drift = (f"{p['drift_ratio']}x measured/predicted")
            if p.get("calibration_id"):
                drift += f" (calibration {p['calibration_id']})"
            lines.append(f"  {'drift':<28} {drift}")
    if "programs" in summary:
        lines.append("Programs")
        for p in summary["programs"]:
            flops = ("-" if p.get("flops") is None
                     else f"{p['flops']:.3g}")
            comp = ("-" if p.get("compile_s") is None
                    else f"{p['compile_s']:.3f}s")
            lines.append(
                f"  {p['program']:<28} flops={flops:<10} "
                f"peak_hbm={_device.human_bytes(p.get('peak_hbm_bytes')):<10} "
                f"compile={comp}")
        if "peak_hbm_bytes_sum" in summary:
            lines.append(f"  {'TOTAL (all resident)':<28} "
                         f"peak_hbm="
                         f"{_device.human_bytes(summary['peak_hbm_bytes_sum'])}")
    if "device_time" in summary:
        lines.append("Device time")
        for e in summary["device_time"]:
            name = e.get("program") or e.get("module") or "?"
            meas = e.get("device_ms_per_call")
            pred = e.get("predicted_ms_per_call")
            parts = []
            if meas is not None:
                parts.append(f"measured={meas}ms/call"
                             + (f" x{e['calls']}" if e.get("calls")
                                else ""))
            elif e.get("device_ms") is not None:
                parts.append(f"total={e['device_ms']}ms")
            if pred is not None:
                parts.append(f"predicted={pred}ms")
                if isinstance(meas, (int, float)) and pred:
                    parts.append(f"ratio={meas / pred:.2f}")
            if e.get("collective_ms"):
                parts.append(f"comm={e['collective_ms']}ms"
                             f"(exposed "
                             f"{e.get('exposed_collective_ms')}ms)")
            if e.get("coarse"):
                parts.append("[coarse]")
            lines.append(f"  {name:<28} " + " ".join(parts))
        for key in ("comm_ms_est", "comm_exposed_ms_est"):
            # The overlap grad-sync ESTIMATES next to the trace-derived
            # measurement above — predicted vs ground truth for the
            # exposed-comm story too.
            if key in summary:
                lines.append(f"  {key:<28} {summary[key]}ms "
                             f"(step-record estimate)")
        if "device_time_null_records" in summary:
            lines.append(f"  {'null_records':<28} "
                         f"{summary['device_time_null_records']} "
                         f"(absent/coarse profiler data)")
    if "hosts" in summary:
        lines.append("Hosts")
        for host, entry in summary["hosts"].items():
            parts = [f"records={entry.get('records')}"]
            for key in ("last_step", "step_ms_p50", "serve_requests"):
                if key in entry:
                    parts.append(f"{key}={entry[key]}")
            lines.append(f"  process {host:<20} " + " ".join(parts))
    if "recovery_counts" in summary:
        lines.append("Recovery")
        for kind, n in summary["recovery_counts"].items():
            lines.append(f"  {kind:<28} {n}")
        if "swap_seconds_total" in summary:
            lines.append(f"  {'swap_seconds_total':<28} "
                         f"{summary['swap_seconds_total']}")
        if "mesh_changes" in summary:
            lines.append(f"  {'mesh_changes':<28} "
                         f"{summary['mesh_changes']} "
                         f"({summary['mesh_change_path']})")
        if "reshard_seconds_total" in summary:
            lines.append(f"  {'reshard_seconds_total':<28} "
                         f"{summary['reshard_seconds_total']}")
    if "fleet" in summary:
        fl = summary["fleet"]
        lines.append("Fleet")
        head = []
        for key in ("requests", "requests_done", "requests_shed",
                    "requests_lost"):
            if key in fl:
                head.append(f"{key.replace('requests_', '')}="
                            f"{fl[key]}")
        if head:
            lines.append(f"  {'requests':<28} " + " ".join(head))
        avail = []
        for key in ("quarantines", "rejoins", "deaths", "restarts",
                    "shed_events"):
            if key in fl:
                avail.append(f"{key}={fl[key]}")
        if avail:
            lines.append(f"  {'availability':<28} " + " ".join(avail))
        loop_bits = []
        for key in ("rolling_swaps", "staleness_max_steps",
                    "tokens_per_sec", "wall_s"):
            if key in fl:
                loop_bits.append(f"{key}={fl[key]}")
        if loop_bits:
            lines.append(f"  {'train->serve':<28} "
                         + " ".join(loop_bits))
        rec_bits = []
        for key in ("recovery_requests", "ttft_ms_p99_recovery",
                    "ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99"):
            if key in fl:
                rec_bits.append(f"{key}={fl[key]}")
        if rec_bits:
            lines.append(f"  {'latency':<28} " + " ".join(rec_bits))
        if "dispatch_retry_hist" in fl:
            hist = " ".join(f"{k}x:{v}" for k, v in
                            fl["dispatch_retry_hist"].items())
            lines.append(f"  {'dispatch_retry_hist':<28} {hist}")
        if "shed_by_class" in fl and fl["shed_by_class"]:
            lines.append(f"  {'shed_by_class':<28} "
                         f"{fl['shed_by_class']}")
        e2e_bits = [f"{k}={v}" for k, v in sorted(fl.items())
                    if k.startswith("ttft_ms_p50_")
                    or k.startswith("ttft_ms_p95_")]
        if "e2e_ms_p95" in fl:
            e2e_bits.append(f"e2e_ms_p95={fl['e2e_ms_p95']}")
        if e2e_bits:
            lines.append(f"  {'e2e latency (per class)':<28} "
                         + " ".join(e2e_bits))
        if "slo" in fl:
            se = fl["slo"]
            bits = [f"alerts={se.get('alerts', 0)}",
                    f"oks={se.get('oks', 0)}"]
            if "budget_remaining_min" in se:
                bits.append(
                    f"budget_min={se['budget_remaining_min']}")
            if se.get("alerts_by_target"):
                bits.append(str(se["alerts_by_target"]))
            lines.append(f"  {'fleet slo':<28} " + " ".join(bits))
        if "decomposition" in fl:
            de = fl["decomposition"]
            lines.append(
                f"  {'decomposition (mean ms)':<28} "
                f"e2e={de.get('e2e_ms_mean', 0)} = "
                f"router_q {de.get('router_queue_ms_mean', 0)} + "
                f"inbox {de.get('inbox_lag_ms_mean', 0)} + "
                f"replica_q {de.get('replica_queue_ms_mean', 0)} + "
                f"prefill {de.get('prefill_ms_mean', 0)} + "
                f"decode {de.get('decode_ms_mean', 0)} + "
                f"absorb {de.get('absorb_ms_mean', 0)} + "
                f"residual {de.get('residual_ms_mean', 0)}"
                + (f" (frac={de['residual_frac_mean']})"
                   if "residual_frac_mean" in de else ""))
        for name, entry in (fl.get("replicas") or {}).items():
            bits = " ".join(f"{k}={v}" for k, v in
                            sorted(entry.items()))
            lines.append(f"  replica {name:<20} {bits}")
    if "slo" in summary:
        lines.append("SLO")
        for target, entry in summary["slo"].items():
            parts = [f"alerts={entry.get('alerts', 0)}"]
            if "worst_burn_fast" in entry:
                parts.append(
                    f"worst_burn_fast={entry['worst_burn_fast']:.2f}")
            if "budget_remaining" in entry:
                parts.append(
                    f"budget_remaining={entry['budget_remaining']}")
            lines.append(f"  {target:<28} " + " ".join(parts))
    if "snapshot_last" in summary:
        lines.append("Snapshot (final)")
        entry = summary["snapshot_last"]
        for key in sorted(entry):
            lines.append(f"  {key:<28} {entry[key]}")
    if "tune" in summary:
        lines.append("Autopilot")
        entry = summary["tune"]
        for key in sorted(entry):
            lines.append(f"  {key:<28} {entry[key]}")
    if "anomalies" in summary:
        lines.append("Anomalies")
        entry = summary["anomalies"]
        for det, n in entry.get("by_detector", {}).items():
            lines.append(f"  {det:<28} {n}")
        last = entry.get("last", {})
        if last:
            lines.append(
                f"  {'last':<28} {last.get('detector')} "
                f"severity={last.get('severity')} "
                f"step={last.get('step')}")
    if "postmortem_bundles" in summary:
        lines.append("Postmortem bundles")
        for path in summary["postmortem_bundles"]:
            lines.append(f"  {path} (render: python -m "
                         f"tensorflow_distributed_tpu.observe"
                         f".postmortem {path})")
    if "health" in summary:
        lines.append("Health")
        for module, entry in summary["health"].items():
            parts = []
            if "worst_update_ratio" in entry:
                parts.append(
                    f"worst_update_ratio={entry['worst_update_ratio']:.2e}"
                    f"@{entry['worst_update_ratio_step']}")
            if "grad_norm_first" in entry:
                parts.append(
                    f"grad_norm={entry['grad_norm_first']:.3g}->"
                    f"{entry['grad_norm_last']:.3g}")
            for key in ("param_rms_last", "act_rms_last"):
                if key in entry:
                    parts.append(f"{key}={entry[key]:.3g}")
            lines.append(f"  {module:<28} " + " ".join(parts))
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.observe.report",
        description=__doc__)
    parser.add_argument("jsonl", nargs="+",
                        help="metrics JSONL(s) written by the observe "
                        "JSONL sink — multiple host-tagged streams "
                        "merge into one report")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON object instead of text")
    args = parser.parse_args(argv)
    try:
        records = []
        for path in args.jsonl:
            records.extend(load_records(path))
    except (OSError, ValueError) as e:
        print(f"observe.report: {e}", file=sys.stderr)
        return 1
    summary = summarize(records)
    print(json.dumps(summary) if args.json else render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
