"""Summarize a metrics JSONL: ``python -m tensorflow_distributed_tpu.observe.report <metrics.jsonl>``.

Regenerates the headline numbers a BENCH artifact wants — p50/p95 step
time, mean throughput and MFU, goodput % — from the raw JSONL the
:mod:`observe.registry` JSONL sink wrote, so bench records can always
be re-derived from (and audited against) the primary artifact.

``--json`` prints one machine-readable JSON object instead of the
human table.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def load_records(path: str) -> List[Dict[str, Any]]:
    records = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i}: not JSON ({e})") from None
    return records


def _mean(values: List[float]) -> float:
    return sum(values) / len(values)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy dependency here)."""
    idx = min(len(sorted_vals) - 1,
              max(0, round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def summarize(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate step/summary events into the report dict."""
    steps = [r for r in records if r.get("event") == "step"]
    summaries = [r for r in records if r.get("event") == "summary"]
    out: Dict[str, Any] = {"records": len(records),
                           "step_records": len(steps)}
    # Serve-mode records (serve/scheduler.py): per-request
    # serve_request rows + one serve_summary — reported alongside the
    # training summary so one JSONL tells the whole story.
    serve_reqs = [r for r in records if r.get("event") == "serve_request"]
    serve_sums = [r for r in records if r.get("event") == "serve_summary"]
    if serve_reqs:
        out["serve_requests"] = len(serve_reqs)
        ttfts = sorted(float(r["ttft_ms"]) for r in serve_reqs
                       if isinstance(r.get("ttft_ms"), (int, float)))
        if ttfts:
            out["serve_ttft_ms_p50"] = round(_percentile(ttfts, 50), 3)
            out["serve_ttft_ms_p95"] = round(_percentile(ttfts, 95), 3)
        toks = [float(r["tok_ms"]) for r in serve_reqs
                if isinstance(r.get("tok_ms"), (int, float))]
        if toks:
            out["serve_tok_ms_mean"] = round(_mean(toks), 4)
    if serve_sums:
        final = serve_sums[-1]
        for key in ("tokens_per_sec", "mean_slot_occupancy",
                    "total_new_tokens", "prefill_compiles"):
            if key in final:
                out[f"serve_{key}"] = final[key]
    if steps:
        out["last_step"] = max(int(r.get("step", 0)) for r in steps)
        # The freshest rolling-window stats (each step record carries
        # the window's p50/p95 at that point; the last one covers the
        # run's tail — the steady state).
        for key in ("step_ms_p50", "step_ms_p95", "data_ms",
                    "dispatch_ms", "device_ms"):
            vals = [r[key] for r in steps if key in r]
            if vals:
                out[key] = round(vals[-1], 3)
        for key in ("tokens_per_sec", "images_per_sec", "items_per_sec",
                    "model_tflops", "mfu", "hw_mfu"):
            vals = [float(r[key]) for r in steps
                    if isinstance(r.get(key), (int, float))]
            if vals:
                out[f"mean_{key}"] = round(_mean(vals), 4)
        losses = [float(r["loss"]) for r in steps
                  if isinstance(r.get("loss"), (int, float))]
        if losses:
            out["first_loss"], out["last_loss"] = (round(losses[0], 5),
                                                   round(losses[-1], 5))
    if summaries:
        final = summaries[-1]
        for key, val in final.items():
            if key.endswith("_seconds") or key == "goodput":
                out[key] = val
    return out


def render(summary: Dict[str, Any]) -> str:
    lines = ["observe.report"]
    order = ("records", "step_records", "last_step", "step_ms_p50",
             "step_ms_p95", "data_ms", "dispatch_ms", "device_ms",
             "mean_tokens_per_sec", "mean_images_per_sec",
             "mean_items_per_sec", "mean_model_tflops", "mean_mfu",
             "mean_hw_mfu", "first_loss", "last_loss", "goodput",
             "serve_requests", "serve_ttft_ms_p50", "serve_ttft_ms_p95",
             "serve_tok_ms_mean", "serve_tokens_per_sec",
             "serve_mean_slot_occupancy", "serve_total_new_tokens",
             "serve_prefill_compiles")
    for key in order:
        if key in summary:
            lines.append(f"  {key:<22} {summary[key]}")
    extras = [k for k in sorted(summary) if k not in order]
    for key in extras:
        lines.append(f"  {key:<22} {summary[key]}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.observe.report",
        description=__doc__)
    parser.add_argument("jsonl", help="metrics JSONL written by the "
                        "observe JSONL sink")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON object instead of text")
    args = parser.parse_args(argv)
    try:
        records = load_records(args.jsonl)
    except (OSError, ValueError) as e:
        print(f"observe.report: {e}", file=sys.stderr)
        return 1
    summary = summarize(records)
    print(json.dumps(summary) if args.json else render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
