"""Metrics registry: one emission path, pluggable sinks.

The reference's observability was bare ``print()`` timestamps and a
hand-maintained 6-line ``performance`` file; our first reproduction of
it (utils/logging.py) kept the print but structured the rows. This
module is the next step: every run event flows through ONE registry as
a flat dict record, tagged with host identity (``process_index``, mesh
shape, config hash), and fans out to whichever sinks the run
configured — pretty stdout, append-per-record JSONL (the durable
artifact format every bench/report tool consumes), or CSV.

Emission is chief-only by construction (``enabled=False`` on non-chief
processes silences the sinks) but the in-memory ring buffer fills on
every process, so library callers can still inspect what WOULD have
been written. The buffer is bounded (``max_records``) so multi-million
step runs don't grow host memory without bound.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, Iterable, Mapping, Optional, TextIO


def config_hash(cfg: Any) -> str:
    """Short stable hash of a config dataclass (or any JSON-able
    mapping) — lets two JSONL files be compared run-to-run without
    carrying the whole config in every record."""
    import dataclasses

    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        cfg = dataclasses.asdict(cfg)
    blob = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:10]


def host_tags(mesh: Any = None, cfg: Any = None) -> Dict[str, Any]:
    """Standard record tags: process identity, mesh shape, config hash.

    ``mesh`` may be a jax Mesh (its ``.shape`` mapping is rendered
    compactly, e.g. ``"data=8"``) or None.
    """
    import jax

    tags: Dict[str, Any] = {"process_index": jax.process_index()}
    if mesh is not None:
        shape = dict(mesh.shape)
        tags["mesh"] = ",".join(f"{k}={v}" for k, v in shape.items()
                                if v > 1) or "data=1"
    if cfg is not None:
        tags["config_hash"] = config_hash(cfg)
    return tags


def git_sha(short: bool = True) -> Optional[str]:
    """The repo's HEAD sha (short by default), or None outside a git
    checkout / without git — artifacts degrade to an explicit null
    stamp rather than failing a bench over provenance."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short" if short else "HEAD",
             *(["HEAD"] if short else [])],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except Exception:
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def artifact_stamp(calibration: str = "") -> Dict[str, Any]:
    """Provenance tags every bench artifact carries (the regress
    ledger names what changed between two artifacts with them):
    the git sha the run was built at and the calibration-profile id
    in effect (None when uncalibrated / unstamped). ``calibration``
    is a calibration.json path; unreadable files degrade to None."""
    cal_id = None
    if calibration:
        try:
            with open(calibration) as f:
                cal_id = json.load(f).get("calibration_id")
        except Exception:
            cal_id = None
    return {"git_sha": git_sha(), "calibration_id": cal_id}


class Sink:
    """A metrics sink consumes flat dict records, one per emit."""

    def emit(self, record: Mapping[str, Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass


class StdoutSink(Sink):
    """The human-facing pretty printer (the MetricLogger format —
    ``[step N] t=...s k=v``) for step records; other events print as
    one JSON line."""

    def __init__(self, stream: Optional[TextIO] = None):
        self.stream = stream if stream is not None else sys.stdout

    def emit(self, record: Mapping[str, Any]) -> None:
        if record.get("event") == "step" and "step" in record:
            skip = {"event", "step", "t", "process_index", "mesh",
                    "config_hash"}
            parts = " ".join(
                f"{k}={v:.6g}" for k, v in record.items()
                if k not in skip and isinstance(v, (int, float)))
            print(f"[step {record['step']:>6}] t={record['t']:8.2f}s "
                  f"{parts}", file=self.stream, flush=True)
        else:
            print(json.dumps(dict(record)), file=self.stream, flush=True)


class JsonlSink(Sink):
    """One JSON object per record — the durable artifact format.

    Opens lazily on first emit (a configured-but-never-used sink leaves
    no file). Fresh runs TRUNCATE any previous file (the repo-wide
    rule: reruns replace, never silently accumulate stale lines — a
    mixed file would skew observe.report's aggregates); a RESUMED run
    passes ``append=True`` so the pre-preemption records the per-record
    flushing preserved stay in the artifact (observe.hub wires this to
    ``cfg.resume``). Flushes per record either way, so a killed run's
    JSONL is complete up to the last emission.
    """

    def __init__(self, path: str, append: bool = False):
        self.path = path
        self.append = append
        self._f: Optional[TextIO] = None
        self._closed = False

    def emit(self, record: Mapping[str, Any]) -> None:
        if self._closed:
            # A straggler emit (e.g. a background writer's retry
            # event racing run teardown) must not LAZILY REOPEN the
            # file — mode "w" would truncate the finished artifact.
            return
        if self._f is None:
            self._f = open(self.path, "a" if self.append else "w")
        self._f.write(json.dumps(dict(record)) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._closed = True
        if self._f is not None:
            self._f.close()
            self._f = None


def default_calibration_path() -> str:
    """The repo-root ``calibration.json`` when one exists (the profile
    benchmarks/calibbench.py fits and commits), else "" — the
    calibration id benches stamp artifacts with by default."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        "calibration.json")
    return path if os.path.exists(path) else ""


def write_jsonl(path: str, records: Iterable[Mapping[str, Any]],
                stamp: bool = True) -> None:
    """One-shot JSONL writer for benchmark outputs (overwrites — reruns
    replace, never silently accumulate stale lines). Every record is
    STAMPED with provenance — the git sha the bench ran at and the
    repo calibration profile's id (explicit record keys win; nulls
    when untracked/uncalibrated) — so the regress ledger can name what
    changed between two artifacts."""
    extra = artifact_stamp(default_calibration_path()) if stamp else {}
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps({**extra, **dict(rec)}) + "\n")


class CsvSink(Sink):
    """Buffered CSV: rows collect in memory and the file is written on
    ``close()`` with the UNION of all keys as the header (sorted,
    missing cells empty) — late-appearing columns like mfu (which needs
    one throughput window first) still get a column. Convenience
    format; the per-record-flushed JSONL sink is the crash-durable one.

    ``events`` restricts which event types land in the table (default
    ``("step",)`` — a clean per-step spreadsheet); ``events=None``
    takes everything. ``max_rows`` bounds the buffer like the
    registry's ``max_records`` (oldest rows drop first), keeping host
    memory bounded on multi-million-step runs.
    """

    def __init__(self, path: str, events: Optional[tuple] = ("step",),
                 max_rows: int = 100_000):
        self.path = path
        self.events = events
        self._rows: collections.deque = collections.deque(
            maxlen=max_rows)

    def emit(self, record: Mapping[str, Any]) -> None:
        if self.events is None or record.get("event") in self.events:
            self._rows.append(dict(record))

    def close(self) -> None:
        import csv

        if not self._rows:
            return
        fields = sorted({k for row in self._rows for k in row})
        with open(self.path, "w", newline="") as f:
            writer = csv.DictWriter(f, fields, restval="")
            writer.writeheader()
            writer.writerows(self._rows)
        self._rows.clear()


# --- module-level indirection (resilience / train.checkpoint) ----------
#
# Deep library code (checkpoint retries, watchdog stalls, quarantines)
# must emit recovery events through the RUN's registry without the
# run threading a registry handle through every call — the same
# pattern observe.goodput uses for its active counter. The Observatory
# installs its registry here; emit_event is a no-op without one, so
# the library modules stay importable and free outside a training run.

_active_registry: Optional["MetricsRegistry"] = None


def set_active(registry: Optional["MetricsRegistry"]) -> None:
    """Install the run's registry (observe.hub.Observatory does)."""
    global _active_registry
    _active_registry = registry


def get_active() -> Optional["MetricsRegistry"]:
    return _active_registry


def emit_event(event: str, **fields: Any) -> None:
    """Emit through the active registry; no-op when none is installed.

    The resilience subsystem routes every recovery event (checkpoint
    retries, quarantines, stall detections, injected faults) through
    here so they land in the same JSONL/CSV artifacts as step records.
    """
    if _active_registry is not None:
        _active_registry.emit(event, **fields)


class MetricsRegistry:
    """Collects records, tags them, and fans out to sinks.

    ``enabled=False`` (non-chief processes) keeps the ring buffer but
    silences every sink — chief-only emission with library-level
    inspectability everywhere.
    """

    def __init__(self, sinks: Iterable[Sink] = (), enabled: bool = True,
                 tags: Optional[Mapping[str, Any]] = None,
                 max_records: int = 100_000, clock=time.time,
                 validate: bool = False):
        self.sinks = list(sinks)
        self.enabled = enabled
        self.validate = validate
        self.tags = dict(tags or {})
        self.records: collections.deque = collections.deque(
            maxlen=max_records)
        self._clock = clock
        self._t0 = clock()
        # emit() is no longer main-thread-only: the background
        # checkpoint writer emits ckpt_retry recovery events
        # concurrently with the loop's step records. One lock keeps
        # sink writes whole-line (JSONL lazy-open included) and the
        # ring buffer consistent.
        self._lock = threading.Lock()

    def emit(self, event: str, **fields: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "event": event,
            "t": round(self._clock() - self._t0, 6),
            **self.tags, **fields,
        }
        if self.validate:
            # Armed under --check only (observe.hub): a record that
            # violates observe/schemas.py is a bug in the EMITTER, and
            # check mode exists to surface exactly that class of bug
            # loudly instead of shipping a malformed artifact.
            from tensorflow_distributed_tpu.observe import schemas
            errors = schemas.validate_record(event, rec)
            if errors:
                raise ValueError(
                    f"observe record {event!r} violates its declared "
                    f"schema: " + "; ".join(errors))
        with self._lock:
            self.records.append(rec)
            if self.enabled:
                for sink in self.sinks:
                    sink.emit(rec)
        return rec

    def close(self) -> None:
        # Under the same lock as emit(): the background checkpoint
        # writer may be emitting a ckpt_retry record while an
        # exception path tears the run down — closing the sink file
        # mid-write would raise from inside the writer's retry loop.
        with self._lock:
            for sink in self.sinks:
                sink.close()
