"""Fleet-level tracing: the router's own span emitter plus the
cross-replica trace stitcher.

PR 15 made serving a *fleet* — a router process dispatching to N
serve-replica processes — but every trace we could render was still
per-process: replica r0's ``ServeTracer`` file shows its half of a
failover and nothing else. This module closes that gap in two parts.

**FleetTracer** is the router's span recorder (cat ``fleet``), built
on the same :class:`~.trace.ChromeTracer` primitives the serve tracer
uses: one async ``request`` span per client request (submit ->
done/shed), a ``client_queue`` child that reopens on every
retry/re-dispatch (time the request spent back at the router), and one
``dispatch`` span per generation keyed by the PR-15 wire id
``gen_rid = rid*1024 + dispatches`` — deliberately the SAME id the
replica-side scheduler sees, so the stitched timeline joins router and
replica spans for one leg by id alone. Quarantines, rejoins, deaths,
restarts, retries and re-dispatches land as instants; waiting/inflight
ride counter tracks.

**stitch()** merges the router trace with every replica's ServeTracer
file into ONE balanced Perfetto timeline. Processes share no
``perf_counter`` origin, so each file carries a ``clock_sync``
metadata anchor (wall time at ts=0, written by ChromeTracer) and each
replica gets a clock *offset* estimated from the snapshot liveness
triplet: the replica stamps ``wall_ts`` (its clock) into
``snapshot.json`` and the filesystem stamps mtime (the router's
frame), so ``median(mtime - wall_ts)`` is that replica's skew —
:func:`estimate_offset`. Sources whose file is torn (a SIGKILL
mid-rename) are skipped with a marker instant rather than sinking the
merge. A replica killed mid-request leaves unmatched ``b`` spans; the
stitcher closes them (``process_death=True``) at the router's
``redispatch``/``retry`` instant for that generation — that IS when
the fleet declared the leg dead — so the merged file is balanced by
construction and a SIGKILL failover renders as router-queue ->
replica-A prefill/decode -> process_death -> re-dispatch -> replica-B
continuation on a single track.

**decompose()** reads the merged timeline back into per-request
latency decompositions: router queue vs inbox-poll lag vs replica
queue vs prefill vs decode (vs residual), per generation — the
breakdown fleetobsbench gates against measured end-to-end latency.

Pure stdlib; every FleetTracer method is a no-op when unconfigured.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json
from tensorflow_distributed_tpu.observe.trace import (
    ChromeTracer, load_trace, unbalanced_async)

_CAT = "fleet"

#: gen_rid = rid * _GEN_BASE + dispatch_ordinal (fleet/router.py).
_GEN_BASE = 1024


def gen_to_rid(gen_rid: int) -> int:
    """The client rid a wire (generation) id belongs to."""
    return int(gen_rid) // _GEN_BASE


class FleetTracer:
    """Router-side span/instant/counter recorder (cat ``fleet``)."""

    def __init__(self, path: str = "", enabled: bool = True,
                 clock=time.perf_counter, max_events: int = 200_000):
        self.tracer = ChromeTracer(path, pid=0, enabled=enabled,
                                   process_name="tfd-router",
                                   clock=clock, max_events=max_events)
        self.enabled = self.tracer.enabled
        self._queued: set = set()      # rids with an open client_queue
        self._dispatch: Dict[int, int] = {}  # rid -> open gen_rid

    # -- request lifecycle (router) ---------------------------------------

    def request_queued(self, rid: int, slo: str = "standard",
                       prompt_len: int = 0) -> None:
        if not self.enabled:
            return
        self.tracer.async_begin("request", rid, cat=_CAT, slo=slo,
                                prompt_len=prompt_len)
        self.tracer.async_begin("client_queue", rid, cat=_CAT)
        self._queued.add(int(rid))

    def dispatch(self, rid: int, gen_rid: int, replica: str,
                 retry: int = 0) -> None:
        """A generation leaves for a replica: close the client-queue
        span, open the ``dispatch`` span under the WIRE id (the same
        id the replica's own trace uses for this leg)."""
        if not self.enabled:
            return
        if int(rid) in self._queued:
            self.tracer.async_end("client_queue", rid, cat=_CAT)
            self._queued.discard(int(rid))
        self.tracer.async_begin("dispatch", gen_rid, cat=_CAT,
                                rid=int(rid), replica=replica,
                                retry=int(retry))
        self._dispatch[int(rid)] = int(gen_rid)

    def first_token(self, rid: int, gen_rid: int,
                    replica: str = "") -> None:
        if not self.enabled:
            return
        self.tracer.instant("first_token", cat=_CAT, rid=int(rid),
                            gen=int(gen_rid), replica=replica)

    def leg_failed(self, rid: int, gen_rid: int, replica: str,
                   why: str) -> None:
        """A dispatched generation died under the request (replica
        death/quarantine evacuation or a dispatch timeout): close its
        dispatch span, drop the ``redispatch`` instant the stitcher
        uses to close the dead replica's spans, and reopen the
        client-queue span — the request is back at the router."""
        if not self.enabled:
            return
        if self._dispatch.get(int(rid)) == int(gen_rid):
            del self._dispatch[int(rid)]
            self.tracer.async_end("dispatch", gen_rid, cat=_CAT,
                                  why=why, failed=True)
        self.tracer.instant("redispatch", cat=_CAT, rid=int(rid),
                            gen=int(gen_rid), replica=replica, why=why)
        if int(rid) not in self._queued:
            self.tracer.async_begin("client_queue", rid, cat=_CAT,
                                    why=why)
            self._queued.add(int(rid))

    def request_done(self, rid: int, finish: str, tokens: int = 0,
                     ttft_ms: float = 0.0, retries: int = 0) -> None:
        if not self.enabled:
            return
        gen = self._dispatch.pop(int(rid), None)
        if gen is not None:
            self.tracer.async_end("dispatch", gen, cat=_CAT,
                                  finish=finish)
        if int(rid) in self._queued:
            self.tracer.async_end("client_queue", rid, cat=_CAT)
            self._queued.discard(int(rid))
        self.tracer.async_end("request", rid, cat=_CAT, finish=finish,
                              tokens=int(tokens),
                              ttft_ms=round(float(ttft_ms), 3),
                              retries=int(retries))

    def shed(self, rid: int, reason: str) -> None:
        if not self.enabled:
            return
        self.tracer.instant("shed", cat=_CAT, rid=int(rid),
                            reason=reason)
        self.request_done(rid, finish="shed:" + reason)

    # -- fleet health instants + counters ---------------------------------

    def replica_event(self, name: str, replica: str,
                      **args: Any) -> None:
        """quarantine / rejoin / replica_death / replica_restart —
        flushed immediately: these are the rare, precious markers a
        router that dies next must still leave on disk."""
        if not self.enabled:
            return
        self.tracer.instant(name, cat=_CAT, replica=replica, **args)
        self.tracer.flush()

    def counters(self, **values: float) -> None:
        if not self.enabled:
            return
        for name, value in values.items():
            self.tracer.counter(name, **{name: value})

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        if self.enabled:
            for gen in list(self._dispatch.values()):
                self.tracer.async_end("dispatch", gen, cat=_CAT,
                                      finish="open_at_close")
            self._dispatch.clear()
            for rid in list(self._queued):
                self.tracer.async_end("client_queue", rid, cat=_CAT)
            self._queued.clear()
            for ev in unbalanced_async(self.tracer._events):
                if ev.get("ph") == "b":
                    self.tracer.async_end(ev["name"], ev.get("id"),
                                          cat=ev.get("cat", _CAT),
                                          finish="open_at_close")
        self.tracer.close()


# -- clock-offset estimation ----------------------------------------------


def estimate_offset(samples: Sequence[Tuple[float, float]]
                    ) -> float:
    """Per-replica clock skew from snapshot ``(wall_ts, mtime)``
    pairs: each pair is one observation of ``mtime - wall_ts`` (the
    replica stamped its clock into the payload; the filesystem stamped
    the router's frame onto the file). The median shrugs off the odd
    pair where the router polled a snapshot long after it was written
    — write and stamp happen in the same rename, so the per-sample
    noise is write latency, not poll latency."""
    if not samples:
        return 0.0
    deltas = sorted(float(m) - float(w) for w, m in samples)
    n = len(deltas)
    mid = n // 2
    if n % 2:
        return deltas[mid]
    return 0.5 * (deltas[mid - 1] + deltas[mid])


def _first_clock_sync(events: List[Dict[str, Any]]) -> Optional[float]:
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "clock_sync":
            try:
                return float(ev["args"]["wall_ts"])
            except (KeyError, TypeError, ValueError):
                return None
    return None


# -- the stitcher ---------------------------------------------------------


def stitch(router_path: str,
           replicas: Sequence[Tuple[str, str, float]],
           out_path: str) -> Dict[str, Any]:
    """Merge the router trace and every replica trace into one
    balanced timeline at ``out_path``.

    ``replicas`` is ``(name, trace_path, offset_s)`` per source —
    ``offset_s`` from :func:`estimate_offset` (0.0 when no snapshot
    pair was ever observed, e.g. a replica killed before its first
    export). Returns the merge stats fleetobsbench gates on:
    ``sources``/``skipped`` (torn or missing files), ``events``,
    ``closed_at_death`` (dead-leg spans the stitcher closed), and
    ``balanced``.
    """
    sources: List[Tuple[str, List[Dict[str, Any]], float]] = []
    skipped: List[str] = []

    def _load(name: str, path: str, offset_s: float) -> None:
        try:
            events = load_trace(path)
        except (OSError, ValueError, KeyError):
            # Torn mid-rename by a SIGKILL, or never written: the
            # merge must not sink with it.
            skipped.append(name)
            return
        if not isinstance(events, list) or not events:
            skipped.append(name)
            return
        sources.append((name, events, float(offset_s)))

    _load("router", router_path, 0.0)
    for name, path, offset_s in replicas:
        _load(name, path, offset_s)
    if not sources:
        raise ValueError(
            f"fleet stitch: no readable trace among router "
            f"{router_path!r} + {len(replicas)} replicas")

    # Absolute (router-frame wall) start per source: clock_sync anchor
    # + skew offset. A source with no anchor (pre-PR trace) pins to
    # the earliest anchored source so its events still render.
    anchored: List[Tuple[str, List[Dict[str, Any]], Optional[float]]] = []
    for name, events, offset_s in sources:
        anchor = _first_clock_sync(events)
        start = None if anchor is None else anchor + offset_s
        anchored.append((name, events, start))
    known = [s for _, _, s in anchored if s is not None]
    t0 = min(known) if known else 0.0

    merged: List[Dict[str, Any]] = []
    redispatch_ts: Dict[int, float] = {}   # gen_rid -> instant ts (merged)
    request_end: Dict[int, float] = {}     # rid -> router request "e" ts
    source_max: Dict[int, float] = {}      # pid -> max shifted ts
    for pid, (name, events, start) in enumerate(anchored):
        shift_us = 0.0 if start is None else (start - t0) * 1e6
        merged.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": f"fleet:{name}"}})
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    continue   # replaced by the fleet:name row above
                merged.append(ev)
                continue
            ts = float(ev.get("ts", 0.0)) + shift_us
            ev["ts"] = round(ts, 3)
            end = ts + float(ev.get("dur", 0.0))
            source_max[pid] = max(source_max.get(pid, 0.0), end)
            if (pid == 0 and ev.get("ph") == "i"
                    and ev.get("name") in ("redispatch", "retry")):
                gen = ev.get("args", {}).get("gen")
                if gen is not None:
                    redispatch_ts[int(gen)] = ts
            if (pid == 0 and ev.get("ph") == "e"
                    and ev.get("name") == "request"):
                try:
                    request_end[int(ev.get("id"))] = ts
                except (TypeError, ValueError):
                    pass
            merged.append(ev)
    for name in skipped:
        merged.append({
            "ph": "i", "name": f"trace_skipped:{name}", "cat": _CAT,
            "pid": 0, "tid": 0, "s": "p",
            "ts": round(max(source_max.values(), default=0.0), 3)})

    # Dead legs: a replica SIGKILLed mid-request leaves "b" spans with
    # no "e". Close each at the router's redispatch/retry instant for
    # its generation — the fleet-level moment that leg ended — falling
    # back to the router-side request end (shed with no re-dispatch),
    # then to the source's own last event.
    closed = 0
    for ev in unbalanced_async(merged):
        if ev.get("ph") != "b":
            continue
        pid = ev.get("pid", 0)
        end_ts = source_max.get(pid, float(ev.get("ts", 0.0)))
        try:
            wire = int(ev.get("id"))
        except (TypeError, ValueError):
            wire = None
        if wire is not None and pid != 0:
            if wire in redispatch_ts:
                end_ts = redispatch_ts[wire]
            elif gen_to_rid(wire) in request_end:
                end_ts = request_end[gen_to_rid(wire)]
        end_ts = max(end_ts, float(ev.get("ts", 0.0)))
        merged.append({
            "ph": "e", "name": ev["name"], "cat": ev.get("cat"),
            "pid": pid, "tid": 0, "id": ev.get("id"),
            "ts": round(end_ts, 3),
            "args": {"process_death": True}})
        closed += 1

    merged.sort(key=lambda e: (e.get("ph") != "M",
                               float(e.get("ts", 0.0))))
    atomic_write_json(out_path, {"traceEvents": merged,
                                 "displayTimeUnit": "ms"})
    return {
        "sources": len(sources),
        "skipped": len(skipped),
        "events": len(merged),
        "closed_at_death": closed,
        "balanced": not unbalanced_async(merged),
    }


# -- latency decomposition ------------------------------------------------


def _span_index(events: List[Dict[str, Any]]
                ) -> Dict[Tuple[str, str, str], List[Tuple[float, float]]]:
    """(cat, name, id) -> [(begin_ts, end_ts)] intervals, pairing
    b/e stack-wise per key (the merged file is balanced)."""
    open_b: Dict[Tuple[str, str, str], List[float]] = {}
    out: Dict[Tuple[str, str, str], List[Tuple[float, float]]] = {}
    for ev in sorted(events, key=lambda e: float(e.get("ts", 0.0))):
        ph = ev.get("ph")
        if ph not in ("b", "e"):
            continue
        key = (str(ev.get("cat")), str(ev.get("name")),
               str(ev.get("id")))
        ts = float(ev.get("ts", 0.0))
        if ph == "b":
            open_b.setdefault(key, []).append(ts)
        elif open_b.get(key):
            out.setdefault(key, []).append((open_b[key].pop(), ts))
    return out


def decompose(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Per-request latency decomposition from a stitched timeline.

    For every router ``request`` span: ``e2e_ms`` (submit -> done) and
    its components — ``router_queue_ms`` (client_queue spans, retries
    included), and per generation the replica-side ``inbox_lag_ms``
    (router dispatch begin -> replica request begin: dispatch-file
    write + feed poll), ``replica_queue_ms``, ``prefill_ms``,
    ``decode_ms``, and ``absorb_ms`` (replica request end -> router
    dispatch close: the journal tail-poll lag before the router SEES
    the finished tokens — the return half of the file control plane,
    mirror of ``inbox_lag_ms`` on the way in) — plus ``residual_ms``
    (e2e minus all components: clock-offset error, scheduler-loop
    gaps, shed wait). fleetobsbench gates ``|residual| / e2e`` on the
    control run.
    """
    idx = _span_index(events)
    out: List[Dict[str, Any]] = []
    dispatches: Dict[int, List[Tuple[int, float, float]]] = {}
    for (cat, name, sid), spans in idx.items():
        if cat == _CAT and name == "dispatch":
            try:
                gen = int(sid)
            except ValueError:
                continue
            for b, e in spans:
                dispatches.setdefault(gen_to_rid(gen), []).append(
                    (gen, b, e))
    for (cat, name, sid), spans in sorted(idx.items()):
        if cat != _CAT or name != "request":
            continue
        try:
            rid = int(sid)
        except ValueError:
            continue
        b, e = spans[0]
        e2e_ms = (e - b) / 1e3
        queue_ms = sum(
            (qe - qb) / 1e3
            for qb, qe in idx.get((_CAT, "client_queue", sid), []))
        inbox = rq = pf = dec = absorb = 0.0
        gens = []
        for gen, db, de in sorted(dispatches.get(rid, [])):
            gens.append(gen)
            gid = str(gen)
            rep_req = idx.get(("serve", "request", gid), [])
            if rep_req:
                inbox += max(0.0, (rep_req[0][0] - db) / 1e3)
                absorb += max(0.0, (de - rep_req[-1][1]) / 1e3)
            for comp, acc in (("queue", "rq"), ("prefill", "pf"),
                              ("decode", "dec")):
                dur = sum((ce - cb) / 1e3 for cb, ce
                          in idx.get(("serve", comp, gid), []))
                if acc == "rq":
                    rq += dur
                elif acc == "pf":
                    pf += dur
                else:
                    dec += dur
        parts = queue_ms + inbox + rq + pf + dec + absorb
        out.append({
            "rid": rid, "gens": gens,
            "e2e_ms": round(e2e_ms, 3),
            "router_queue_ms": round(queue_ms, 3),
            "inbox_lag_ms": round(inbox, 3),
            "replica_queue_ms": round(rq, 3),
            "prefill_ms": round(pf, 3),
            "decode_ms": round(dec, 3),
            "absorb_ms": round(absorb, 3),
            "residual_ms": round(e2e_ms - parts, 3),
        })
    return out
