"""Step-time breakdown: where each training step's wall time goes.

The loop dispatches steps asynchronously, so a bare per-step delta
(the reference's only timing, mnist_single.py:102-134) conflates three
very different stalls:

- **data wait** — the host blocked in ``next(it)`` because the
  prefetcher ran dry (input pipeline bound);
- **dispatch** — the host issuing the jitted step (tracing/dispatch
  overhead; normally microseconds after compile);
- **device wait** — the host blocked on the oldest in-flight step's
  results (device compute bound — the healthy regime).

This instrument timestamps the loop's phase boundaries with an
injectable clock (tests drive it with a fake), keeps a rolling window
of per-step durations, and reports p50/p95 totals plus per-phase
means. Anything not covered by the three phases (cadence host work:
metric fetch, eval, checkpoint snapshot) lands in ``host``.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — no numpy needed, exact
    on the small rolling windows this module keeps."""
    if not values:
        raise ValueError("percentile of empty sequence")
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1,
                      int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[rank]


class StepTimeBreakdown:
    """Phase-mark API driven by the training loop, one cycle per step::

        st.data_start(); batch = next(it); st.data_end()
        state, m = step_fn(state, batch); st.dispatch_end()
        block_on_oldest();                st.device_end()
        ... cadence host work ...;       st.step_end()

    ``device_end`` is optional (the loop only blocks once the dispatch
    window fills). Missing phases count as zero.
    """

    PHASES = ("data", "dispatch", "device", "host")

    def __init__(self, window: int = 200, clock=time.perf_counter):
        self._clock = clock
        self._win: Dict[str, collections.deque] = {
            p: collections.deque(maxlen=window) for p in self.PHASES}
        self._totals: collections.deque = collections.deque(maxlen=window)
        self._marks: Dict[str, Optional[float]] = {}
        self.steps = 0

    # -- phase marks ------------------------------------------------------
    def data_start(self) -> None:
        self._marks = {"start": self._clock()}

    def data_end(self) -> None:
        self._marks["data"] = self._clock()

    def dispatch_end(self) -> None:
        self._marks["dispatch"] = self._clock()

    def device_end(self) -> None:
        self._marks["device"] = self._clock()

    def step_end(self) -> Dict[str, float]:
        """Close the cycle; returns this step's breakdown in seconds."""
        m = self._marks
        start = m.get("start")
        if start is None:  # marks never opened (disabled caller)
            return {}
        end = self._clock()
        t_data = m.get("data", start)
        t_disp = m.get("dispatch", t_data)
        t_dev = m.get("device", t_disp)
        rec = {
            "data": t_data - start,
            "dispatch": t_disp - t_data,
            "device": t_dev - t_disp,
            "host": end - t_dev,
            "total": end - start,
        }
        for p in self.PHASES:
            self._win[p].append(rec[p])
        self._totals.append(rec["total"])
        self.steps += 1
        self._marks = {}
        return rec

    # -- aggregates -------------------------------------------------------
    def summary(self) -> Dict[str, float]:
        """Rolling-window stats in milliseconds: per-phase means plus
        p50/p95 of the step total. Empty dict before the first step."""
        if not self._totals:
            return {}
        out = {f"{p}_ms": 1e3 * sum(w) / len(w)
               for p, w in self._win.items() if len(w)}
        totals: List[float] = list(self._totals)
        out["step_ms"] = 1e3 * sum(totals) / len(totals)
        out["step_ms_p50"] = 1e3 * percentile(totals, 50)
        out["step_ms_p95"] = 1e3 * percentile(totals, 95)
        return {k: round(v, 4) for k, v in out.items()}
