"""Device-time attribution: parse the profiler's trace, charge wall
time to programs.

``utils/profiling.py`` captures a step-windowed ``jax.profiler`` trace;
until now the artifact was write-only — a TensorBoard/Perfetto file a
human might open. This module closes the loop: it parses the captured
``.trace.json.gz`` (the Perfetto-format export ``start_trace(...,
create_perfetto_trace=True)`` writes beside the XPlane) and attributes
device wall time per INSTRUMENTED PROGRAM NAME (train_step,
serve_decode_step, serve_prefill_b*, ...) and per collective family,
emitting one ``device_time`` JSONL record per program beside the
``compile`` records the program registry (observe/device.py) already
writes. Predicted (roofline-from-cost_analysis) and measured
(trace-derived) step time finally sit in the same artifact — the
ground truth the planner's calibration loop
(analysis/planner/calibrate.py) fits against.

Attribution key: every op event in the trace carries
``args.hlo_module`` — the XLA module name, ``jit_<fn.__name__>`` —
and observe.device.instrument_jit names the pre-jit function after the
program, so module names match registry names exactly. Per-module
device time is the UNION of op-event intervals (ops run concurrently
across device lanes / host threadpool threads; summing would
double-count), op_ms is the plain sum, and collective time is split by
HLO family (all-reduce, all-gather, reduce-scatter, collective-permute,
all-to-all) with an EXPOSED slice: collective wall not overlapped by
any non-collective op of the same module — the measured counterpart of
the overlap grad-sync's ``comm_exposed_ms_est``.

Degradation is a contract (the registry's): a missing trace, a
backend that wrote no attributable op events, or any parse failure
yields records whose measurement fields are explicitly ``None`` (with
a ``reason``), never an exception into the run. On CPU there is no
device timeline — op events come from the host threadpool — so
records are tagged ``coarse: true``; the numbers are real XLA
execution walls, but host-scheduling noise rides them.

Pure stdlib on purpose: the parse tier (and its tests) runs jax-free.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: HLO op-name prefix -> collective family (record field suffix).
COLLECTIVE_FAMILIES = (
    ("all-reduce", "all_reduce"),
    ("all-gather", "all_gather"),
    ("reduce-scatter", "reduce_scatter"),
    ("collective-permute", "collective_permute"),
    ("all-to-all", "all_to_all"),
)

#: Every measurement field a device_time record carries, in record
#: order — explicitly None when the trace yields nothing (the
#: compile-record contract: stable SHAPE everywhere).
DEVICE_TIME_FIELDS = (
    "device_ms", "device_ms_per_call", "op_ms", "calls",
    "collective_ms", "exposed_collective_ms",
)


def sanitize(name: str) -> str:
    """The trace-name normalization instrument_jit applies to
    ``fn.__name__`` (XLA module names come from it): one place, so
    attribution can re-apply it when matching registry names."""
    return re.sub(r"[^0-9A-Za-z_]", "_", name)


def find_trace_file(log_dir: str) -> Optional[str]:
    """Newest captured Perfetto trace under a ``jax.profiler`` log
    dir (``plugins/profile/<run>/<host>.trace.json.gz``). None when
    nothing was captured."""
    runs = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*")))
    for run in reversed(runs):  # newest run dir first (timestamp names)
        files = sorted(glob.glob(os.path.join(run, "*.trace.json.gz")))
        named = [f for f in files
                 if not f.endswith("perfetto_trace.json.gz")]
        if named or files:
            return (named or files)[0]
    return None


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Event list of one Chrome/Perfetto trace file (.json or
    .json.gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("traceEvents", []))
    return list(data)  # bare event-array form


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals (µs)."""
    total, cur_s, cur_e = 0.0, None, None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _overlap_us(a: List[Tuple[float, float]],
                b: List[Tuple[float, float]]) -> float:
    """Length of union(a) ∩ union(b) (µs) — two-pointer merge over the
    already-unioned interval lists."""
    def merged(iv):
        out: List[List[float]] = []
        for s, e in sorted(iv):
            if out and s <= out[-1][1]:
                out[-1][1] = max(out[-1][1], e)
            else:
                out.append([s, e])
        return out

    xs, ys = merged(a), merged(b)
    i = j = 0
    total = 0.0
    while i < len(xs) and j < len(ys):
        s = max(xs[i][0], ys[j][0])
        e = min(xs[i][1], ys[j][1])
        if e > s:
            total += e - s
        if xs[i][1] <= ys[j][1]:
            i += 1
        else:
            j += 1
    return total


def _collective_family(op: str) -> Optional[str]:
    low = op.lower()
    for prefix, family in COLLECTIVE_FAMILIES:
        if low.startswith(prefix):
            return family
    return None


def attribute(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-HLO-module device-time attribution over one trace's events.

    Returns ``{"coarse": bool, "modules": {module: entry}}`` where each
    entry carries ``wall_us`` (union of op intervals — concurrent lanes
    counted once), ``op_us`` (plain sum), ``ops`` (event count),
    ``calls`` (estimated invocations: the modal per-op-name occurrence
    count — most ops run exactly once per call; ops inside scans
    inflate their own count, not the mode), ``collective_us`` /
    ``exposed_collective_us`` and per-family ``collective_families``.

    ``coarse`` is True when no ``/device:`` process appears in the
    trace (CPU: op events are host-threadpool walls). When device
    processes exist, only THEIR op events are attributed — the device
    timeline is the ground truth, host mirrors are ignored.
    """
    events = list(events)
    device_pids = set()
    for ev in events:
        if (ev.get("ph") == "M" and ev.get("name") == "process_name"
                and str(ev.get("args", {}).get("name", ""))
                .startswith("/device:")):
            device_pids.add(ev.get("pid"))
    coarse = not device_pids

    per: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        args = ev.get("args") or {}
        module = args.get("hlo_module")
        if not module:
            continue
        op = str(args.get("hlo_op") or ev.get("name") or "")
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        entry = per.setdefault(module, {
            "intervals": [], "coll_intervals": [],
            "compute_intervals": [], "op_us": 0.0, "ops": 0,
            "op_counts": {}, "families": {}})
        entry["intervals"].append((ts, ts + dur))
        entry["op_us"] += dur
        entry["ops"] += 1
        entry["op_counts"][op] = entry["op_counts"].get(op, 0) + 1
        family = _collective_family(op)
        if family:
            entry["coll_intervals"].append((ts, ts + dur))
            entry["families"][family] = (
                entry["families"].get(family, 0.0) + dur)
        else:
            entry["compute_intervals"].append((ts, ts + dur))

    modules: Dict[str, Dict[str, Any]] = {}
    for module, e in per.items():
        counts = sorted(e["op_counts"].values())
        # Modal occurrence count = invocations (ties -> smallest mode,
        # the conservative estimate).
        calls = 0
        if counts:
            best, best_n = counts[0], 0
            for c in set(counts):
                n = counts.count(c)
                if n > best_n or (n == best_n and c < best):
                    best, best_n = c, n
            calls = best
        coll_us = _union_us(e["coll_intervals"])
        exposed_us = coll_us - _overlap_us(e["coll_intervals"],
                                           e["compute_intervals"])
        modules[module] = {
            "wall_us": _union_us(e["intervals"]),
            "op_us": e["op_us"],
            "ops": e["ops"],
            "calls": calls,
            "collective_us": coll_us,
            "exposed_collective_us": max(exposed_us, 0.0),
            "collective_families": dict(sorted(e["families"].items())),
        }
    return {"coarse": coarse, "modules": modules}


def match_program(module: str, programs: Iterable[str]) -> Optional[str]:
    """Map an HLO module name back to its instrumented program name:
    ``jit_<sanitized program>`` exactly, else the longest program whose
    sanitized name prefixes the module stem (lowered modules sometimes
    grow numeric suffixes)."""
    stem = module[4:] if module.startswith("jit_") else module
    by_sanitized = {}
    for p in programs:
        by_sanitized.setdefault(sanitize(p), p)
    if stem in by_sanitized:
        return by_sanitized[stem]
    for s in sorted(by_sanitized, key=len, reverse=True):
        if stem.startswith(s):
            return by_sanitized[s]
    return None


def _null_record(reason: str) -> Dict[str, Any]:
    rec: Dict[str, Any] = {"program": None, "module": None,
                           **{k: None for k in DEVICE_TIME_FIELDS},
                           "coarse": None, "reason": reason}
    return rec


def device_time_records(log_dir: str,
                        programs: Iterable[str] = (),
                        max_unmatched: int = 8) -> List[Dict[str, Any]]:
    """The ``device_time`` record payloads for one capture: one record
    per attributed module, matched against ``programs`` (registry
    names). Unmatched modules are still reported (largest first,
    capped) with ``program: null`` — nothing silently dropped. NEVER
    raises; absent or unparseable traces degrade to one explicit-null
    record with the reason."""
    try:
        path = find_trace_file(log_dir)
        if path is None:
            return [_null_record(f"no trace under {log_dir}")]
        attr = attribute(load_trace_events(path))
    except Exception as e:  # degrade, never die: telemetry contract
        return [_null_record(f"{type(e).__name__}: {e}"[:300])]
    modules = attr["modules"]
    if not modules:
        return [_null_record(
            f"{os.path.basename(path)}: no attributable op events "
            f"(profiler data absent or too coarse)")]
    records: List[Dict[str, Any]] = []
    unmatched = 0
    for module, e in sorted(modules.items(),
                            key=lambda kv: -kv[1]["wall_us"]):
        program = match_program(module, programs)
        if program is None:
            unmatched += 1
            if unmatched > max_unmatched:
                continue
        calls = e["calls"] or None
        rec: Dict[str, Any] = {
            "program": program,
            "module": module,
            "device_ms": round(e["wall_us"] / 1e3, 4),
            "device_ms_per_call": (round(e["wall_us"] / 1e3 / calls, 4)
                                   if calls else None),
            "op_ms": round(e["op_us"] / 1e3, 4),
            "calls": calls,
            "collective_ms": round(e["collective_us"] / 1e3, 4),
            "exposed_collective_ms": round(
                e["exposed_collective_us"] / 1e3, 4),
            "coarse": attr["coarse"],
        }
        for family, us in e["collective_families"].items():
            rec[f"coll_{family}_ms"] = round(us / 1e3, 4)
        records.append(rec)
    return records


def with_predictions(records: List[Dict[str, Any]],
                     costs_by_program: Dict[str, Dict[str, Any]],
                     hw: Any = None) -> List[Dict[str, Any]]:
    """Join measured records with each program's roofline prediction
    from its compile-record costs (analysis.planner.score.roofline_ms
    at ``hw``) — the measured-vs-predicted pair observe.report's
    "Device time" section renders and calibrate.py fits. Pure function
    over dicts; records without costs (or a null hw) pass through
    unchanged."""
    if hw is None:
        return records
    from tensorflow_distributed_tpu.analysis.planner.score import (
        roofline_ms)

    out = []
    for rec in records:
        rec = dict(rec)
        costs = costs_by_program.get(rec.get("program") or "")
        if costs:
            pred = roofline_ms(costs, 0.0, hw)
            rec["predicted_ms_per_call"] = pred["step_ms"]
            if getattr(hw, "calibration_id", None):
                rec["calibration_id"] = hw.calibration_id
        out.append(rec)
    return out
