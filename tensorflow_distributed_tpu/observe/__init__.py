"""Unified observability: metrics registry, step-time breakdown, MFU
accounting, Chrome-trace spans, and goodput.

The reference repo's only observability was bare ``print()``
timestamps and a hand-maintained 6-line ``performance`` file; this
package turns every run into structured, comparable data:

- :mod:`observe.registry` — one emission path, pluggable sinks
  (stdout pretty-printer, JSONL, CSV), chief-only emission, host tags;
- :mod:`observe.steptime` — per-step data-wait / dispatch / device
  breakdown with rolling p50/p95;
- :mod:`observe.mfu` — model-FLOPs estimates per family and
  tokens/s / imgs/s / MFU accounting (the benchmarks import from here);
- :mod:`observe.trace` — pure-Python Chrome-trace (Perfetto) spans for
  host phases, no TPU runtime required;
- :mod:`observe.goodput` — productive vs. restore/drain/blocked time;
- :mod:`observe.device` — compiled-program registry: every jit site's
  cost_analysis/memory_analysis (flops, bytes, peak-HBM estimate,
  donated bytes) + lower/compile wall time as ``compile`` records;
- :mod:`observe.health` — on-device per-layer training vitals (grad
  norm, update-to-param ratio, param RMS, activation-RMS taps),
  cadence-gated inside the jitted step;
- :mod:`observe.serve_trace` — per-request async-span trees for
  ``mode=serve`` (one Perfetto file, balanced even across a
  supervised restart);
- :mod:`observe.slo` — live SLO burn-rate monitor: declared
  percentile targets, fast/slow windows on the decode-step clock,
  ``slo_alert``/``slo_ok`` events with error-budget accounting;
- :mod:`observe.anomaly` — online anomaly detection: streaming
  MAD/median/slope detectors over the already-fetched log-cadence
  values (train) and the decode-step clock (serve), ``anomaly``
  records + the live incident state snapshots export;
- :mod:`observe.flightrec` — crash flight recorder: bounded record
  ring, fsync'd snapshots (SIGKILL-durable), postmortem bundles on
  trappable deaths;
- :mod:`observe.postmortem` — ``python -m ...observe.postmortem
  <bundle>``: timeline + likely-cause incident report from a bundle;
- :mod:`observe.hub` — the :class:`Observatory` the train loop drives
  and the :class:`ServeObservatory` bundle serve/run.py drives;
- :mod:`observe.xprof` — device-time attribution: parse the
  profiler's Perfetto export into per-program ``device_time`` records
  (measured device wall + collective families vs roofline predicted);
- :mod:`observe.regress` — the cross-run regression ledger:
  ``python -m ...observe.regress`` compares fresh bench artifacts
  against the committed baselines, exit nonzero on regression;
- :mod:`observe.report` — ``python -m ...observe.report metrics.jsonl
  [more.jsonl ...]`` summarizer (multi-host streams merge, per-host
  sections).

The full record schema every module emits is documented in RECORDS.md.
"""

from tensorflow_distributed_tpu.observe.goodput import (  # noqa: F401
    GoodputCounter)
from tensorflow_distributed_tpu.observe.hub import Observatory  # noqa: F401
from tensorflow_distributed_tpu.observe.mfu import (  # noqa: F401
    PEAK_BF16_FLOPS, ThroughputAccountant, device_peak_flops,
    flops_per_item, flops_per_token)
from tensorflow_distributed_tpu.observe.registry import (  # noqa: F401
    CsvSink, JsonlSink, MetricsRegistry, StdoutSink, config_hash,
    host_tags, write_jsonl)
from tensorflow_distributed_tpu.observe.steptime import (  # noqa: F401
    StepTimeBreakdown)
from tensorflow_distributed_tpu.observe.trace import (  # noqa: F401
    ChromeTracer, load_trace)
