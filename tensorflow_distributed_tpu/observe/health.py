"""On-device model-health telemetry: per-layer training-vitals.

One global ``grad_norm`` says a run diverged; it cannot say WHERE. The
standard per-layer vitals big-model trainers watch are computed here,
INSIDE the jitted step (train/step.py, train/pipeline_step.py call
:func:`stats`), per top-level parameter module (``layer_3``,
``tok_emb``, ``lm_head``, ...):

- ``grad_norm``: the module's gradient norm — a layer whose gradients
  vanish or explode shows before the global norm moves;
- ``update_ratio``: ||optimizer update|| / ||params|| — the classic
  learning-rate vital (healthy training sits around 1e-3; a layer
  pinned at 0 is frozen, one at 1e-1 is being rewritten every step);
- ``param_rms``: RMS of the module's parameters — slow drift here is
  the norm-growth signature that precedes loss spikes;
- optionally ``act_rms`` (``TransformerConfig.health_taps``): RMS of
  each block's output, sown from inside the transformer into the
  transient "health" collection and folded into the same records.

Cadence discipline: the stats are CADENCE-GATED ON DEVICE — a
``lax.cond`` on a traced ``(step + 1) % health_every == 0`` flag
computes the norms only on emitting steps, and the scalars ride the
EXISTING metrics pytree, so off-cadence steps pay neither compute nor
any extra host transfer (the loop's single cadence ``device_get``
already carries the whole dict). The ``health_emit`` metric tells the
host which fetches hold real values.

Host side, :func:`split` separates the health scalars from the task
metrics (so stdout logs stay readable) and :func:`group` reshapes them
into per-module ``health`` records for the registry/JSONL; the report
tool's "Health" section summarizes worst update-ratios and grad-norm
trends per module.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterator, Tuple

PREFIX = "health/"
EMIT_KEY = "health_emit"


# --- inside-jit (device) ------------------------------------------------

def _module_stats(params: Any, grads: Any, updates: Any
                  ) -> Dict[str, Any]:
    """The per-top-level-module vitals, as flat ``health/<module>/<stat>``
    f32 scalars. Runs under jit (called from the step builders)."""
    import jax
    import jax.numpy as jnp
    import optax

    stats: Dict[str, Any] = {}
    if not isinstance(grads, dict) or not grads:
        grads, params, updates = ({"params": grads}, {"params": params},
                                  {"params": updates})
    for key in sorted(grads):
        g = optax.global_norm(grads[key]).astype(jnp.float32)
        u = optax.global_norm(updates[key]).astype(jnp.float32)
        p = optax.global_norm(params[key]).astype(jnp.float32)
        n = sum(x.size for x in jax.tree_util.tree_leaves(params[key]))
        stats[f"{PREFIX}{key}/grad_norm"] = g
        stats[f"{PREFIX}{key}/update_ratio"] = u / (p + 1e-12)
        stats[f"{PREFIX}{key}/param_rms"] = p / math.sqrt(max(n, 1))
    return stats


def stats(params: Any, grads: Any, updates: Any, step: Any,
          health_every: int) -> Dict[str, Any]:
    """Cadence-gated vitals for one step (traced context).

    ``step`` is the state's PRE-increment counter, so the emit flag
    fires exactly when the loop's 1-based step id hits the cadence.
    The ``lax.cond`` puts the norm reductions inside the taken branch:
    off-cadence steps compute a handful of zeros, not O(params) reads.
    """
    import jax
    import jax.numpy as jnp

    modules = (sorted(grads) if isinstance(grads, dict) and grads
               else ["params"])
    keys = [f"{PREFIX}{m}/{s}" for m in modules
            for s in ("grad_norm", "param_rms", "update_ratio")]
    emit = ((step + 1) % health_every) == 0

    def _zeros(p, g, u):
        return {k: jnp.zeros((), jnp.float32) for k in keys}

    def _live(p, g, u):
        return _module_stats(p, g, u)

    out = jax.lax.cond(emit, _live, _zeros, params, grads, updates)
    out[EMIT_KEY] = emit.astype(jnp.float32)
    return out


def gate(metrics: Dict[str, Any], emit: Any) -> Dict[str, Any]:
    """Zero every ``health/`` scalar off-cadence (the activation taps
    are computed in the forward pass regardless — cheap elementwise
    reductions — but must not emit stale values between cadences)."""
    import jax.numpy as jnp

    return {k: (jnp.where(emit, v, jnp.zeros_like(v))
                if k.startswith(PREFIX) else v)
            for k, v in metrics.items()}


def flatten_taps(taps: Any) -> Dict[str, Any]:
    """Sown "health" collection -> flat ``health/<module>/<stat>``
    scalars. Sow appends a tuple per call; one forward sows once, so
    the first element is the value (a scan/accum over microbatches
    means the metrics pipeline averages them downstream)."""
    import jax.numpy as jnp

    flat: Dict[str, Any] = {}

    def walk(node: Any, path: Tuple[str, ...]) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], path + (str(k),))
            return
        if isinstance(node, (tuple, list)):
            node = node[0] if len(node) == 1 else sum(node) / len(node)
        flat[PREFIX + "/".join(path)] = jnp.asarray(
            node, jnp.float32).reshape(())

    walk(taps, ())
    return flat


# --- host side ----------------------------------------------------------

def split(host_metrics: Dict[str, float]
          ) -> Tuple[Dict[str, float], Dict[str, float], bool]:
    """(task metrics, health scalars, emitted?) from one fetched
    metrics dict — the loop logs the first, records the second only
    when the device's emit flag fired."""
    plain = {k: v for k, v in host_metrics.items()
             if not k.startswith(PREFIX) and k != EMIT_KEY}
    health = {k: v for k, v in host_metrics.items()
              if k.startswith(PREFIX)}
    emitted = float(host_metrics.get(EMIT_KEY, 0.0)) > 0
    return plain, health, emitted


def group(health: Dict[str, float]
          ) -> Iterator[Tuple[str, Dict[str, float]]]:
    """``health/<module>/<stat>`` scalars -> per-module field dicts,
    ready to emit as one ``health`` record per module."""
    by_module: Dict[str, Dict[str, float]] = {}
    for key, val in health.items():
        rest = key[len(PREFIX):]
        module, _, stat = rest.rpartition("/")
        if not module:
            module, stat = rest, "value"
        by_module.setdefault(module, {})[stat] = float(val)
    for module in sorted(by_module):
        yield module, by_module[module]
