"""Compiled-program registry: cost/HBM accounting for every jit site.

Host-side observability (steptime, MFU, goodput) says how long a step
took; this module says what XLA actually BUILT. Every jitted program
the framework dispatches — the train/eval/multi/pipelined steps, the
generate/beam programs, the serving engine's bucketed prefills, decode
step, and row insert — routes through :func:`instrument`, which on the
program's first (enabled) invocation lowers + compiles it through the
AOT API and records:

- ``cost_analysis()``: flops and bytes accessed per execution;
- ``memory_analysis()``: argument / output / temp / generated-code
  bytes, the donated (aliased) bytes the ``donate_argnums`` plumbing
  actually saved, and a peak-HBM estimate
  (``arg + out + temp + code - donated``, the residency XLA plans for);
- lowering and compile wall time.

Each registration appends to a process-level registry (:func:`programs`)
and emits a ``compile`` record through the active metrics registry, so
the run's JSONL carries the full program inventory next to its step
records (summarized by ``observe.report``'s "Programs" section and
:func:`budget_table`).

Graceful degradation is a contract, not an accident: backends or jax
versions that expose no analysis (or whose AOT path rejects the
arguments) still register the program — every analysis field is
explicitly ``None`` rather than absent, and the wrapped program always
executes through its ORIGINAL jitted callable, so telemetry can never
take down a run. The extra lower+compile for registration is absorbed
by the persistent compilation cache (utils/compilecache.py) that every
entrypoint enables.

Registration is gated (:func:`set_enabled`) because the AOT pass costs
a second trace: the Observatory turns it on for observed runs
(``--observe.programs``, default true — but only when a sink is
configured), serve/run.py likewise, and library use without either
stays zero-overhead (one bool check per call).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from tensorflow_distributed_tpu.observe.registry import emit_event

_lock = threading.Lock()
_enabled = False
# Bumped on every set_enabled(True): lru-cached programs (generate's
# samplers, the engine's per-bucket prefills) survive across runs in
# one process, and each newly-enabled run deserves its own compile
# records in its own JSONL — a wrapper re-registers once per
# generation, not once per process.
_generation = 0
_programs: List[Dict[str, Any]] = []


def set_enabled(on: bool) -> None:
    """Arm (or disarm) registration. The Observatory calls this from
    ``--observe.programs``; tests and tools may call it directly."""
    global _enabled, _generation
    with _lock:
        if on and not _enabled:
            _generation += 1
        _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def generation() -> int:
    return _generation


def programs() -> List[Dict[str, Any]]:
    """Snapshot of every compile record registered this process."""
    with _lock:
        return [dict(r) for r in _programs]


def reset() -> None:
    """Clear the process-level registry (test isolation)."""
    global _programs
    with _lock:
        _programs = []


def _first_mapping(value) -> Optional[Dict[str, Any]]:
    """cost_analysis() returns a dict on some jax versions and a
    one-per-device list of dicts on others — normalize to one dict."""
    if isinstance(value, (list, tuple)):
        value = value[0] if value else None
    if isinstance(value, dict):
        return value
    return None


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 6)


# Every analysis field a compile record (and a planner score) carries,
# in record order. extract_costs fills them all — explicitly None when
# the backend exposes nothing — so record SHAPE is stable everywhere.
COST_FIELDS = ("flops", "bytes_accessed", "argument_bytes",
               "output_bytes", "temp_bytes", "generated_code_bytes",
               "donated_bytes", "peak_hbm_bytes")


def extract_costs(compiled: Any) -> Dict[str, Any]:
    """``cost_analysis``/``memory_analysis`` of one AOT-compiled
    program, normalized to the :data:`COST_FIELDS` dict.

    THE one place the cross-jax-version key handling lives (dict vs
    per-device list-of-dicts cost_analysis, space-separated cost keys,
    memory_analysis attribute names) with the explicit-null
    degradation contract: a backend exposing no analysis yields a
    dict of ``None`` fields, never a missing key and never a raise.
    Shared by :func:`register_compiled` (the program registry) and
    the auto-layout planner's candidate scoring
    (analysis/planner/score.py)."""
    rec: Dict[str, Any] = {k: None for k in COST_FIELDS}
    if compiled is None:
        return rec
    try:
        cost = _first_mapping(compiled.cost_analysis())
    except Exception:
        cost = None
    if cost:
        if isinstance(cost.get("flops"), (int, float)):
            rec["flops"] = float(cost["flops"])
        if isinstance(cost.get("bytes accessed"), (int, float)):
            rec["bytes_accessed"] = float(cost["bytes accessed"])
    try:
        mem = compiled.memory_analysis()
    except Exception:
        mem = None
    if mem is not None:
        fields = {
            "argument_bytes": "argument_size_in_bytes",
            "output_bytes": "output_size_in_bytes",
            "temp_bytes": "temp_size_in_bytes",
            "generated_code_bytes": "generated_code_size_in_bytes",
            "donated_bytes": "alias_size_in_bytes",
        }
        for key, attr in fields.items():
            v = getattr(mem, attr, None)
            if isinstance(v, (int, float)):
                rec[key] = int(v)
        parts = (rec["argument_bytes"], rec["output_bytes"],
                 rec["temp_bytes"], rec["generated_code_bytes"])
        if all(p is not None for p in parts):
            # What XLA plans to hold resident while the program
            # runs; donated inputs alias their outputs, so they
            # are counted once, not twice.
            rec["peak_hbm_bytes"] = (
                sum(parts) - (rec["donated_bytes"] or 0))
    return rec


def aot_lower_compile(jitted: Callable, args: tuple = (),
                      kwargs: Optional[Dict[str, Any]] = None):
    """``jitted.lower(*args, **kwargs).compile()`` with wall clocks:
    returns ``(lowered, compiled, lower_s, compile_s)``. The ONE AOT
    capture path, shared by :func:`instrument`'s registration pass and
    the planner's candidate scoring — exceptions propagate; callers
    own their degradation policy (the registry degrades to a null
    record, the planner marks the candidate unscoreable)."""
    lower = getattr(jitted, "lower", None)
    if lower is None:
        raise TypeError("no .lower (not a jit callable)")
    t0 = time.perf_counter()
    lowered = lower(*args, **(kwargs or {}))
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    return lowered, compiled, t1 - t0, t2 - t1


def register_compiled(name: str, lowered: Any = None,
                      compiled: Any = None, *,
                      lower_s: Optional[float] = None,
                      compile_s: Optional[float] = None,
                      error: Optional[str] = None) -> Dict[str, Any]:
    """Record one compiled program's cost/memory accounting.

    ``lowered``/``compiled`` are the ``jax.stages`` objects from the
    AOT API (``jitted.lower(...)`` / ``.compile()``); either may be
    None — every analysis field degrades to an explicit ``None`` when
    the backend exposes nothing, so the record's SHAPE is stable across
    platforms and the report can always render the table.
    """
    rec: Dict[str, Any] = {
        "program": name,
        **extract_costs(compiled),
        "lower_s": _round(lower_s),
        "compile_s": _round(compile_s),
    }
    if error:
        rec["error"] = error[:300]
    with _lock:
        _programs.append(rec)
    emit_event("compile", **rec)
    return rec


class _InstrumentedProgram:
    """The :func:`instrument` wrapper: registers on the first enabled
    call, then (and on every later call) delegates to the ORIGINAL jit
    fast path — execution never routes through the slower AOT
    ``Compiled.__call__``, and a failed registration never fails the
    run. Unknown attributes forward to the wrapped PjitFunction
    (``.lower``/``.trace`` — moebench and the 1F1B parity tests drive
    the AOT API on the returned step), while callers may still SET
    their own attributes (pipeline_step's ``observe_hw_recompute``)."""

    def __init__(self, name: str, jitted: Callable):
        self._name = name
        self._jitted = jitted
        self._seen_generation = 0
        self.__wrapped__ = jitted
        self.__name__ = f"instrumented_{name}"

    def __call__(self, *args, **kwargs):
        if _enabled and self._seen_generation != _generation:
            self._seen_generation = _generation
            _register_from(self._name, self._jitted, args, kwargs)
        return self._jitted(*args, **kwargs)

    def __getattr__(self, attr):
        # Only reached for attributes NOT set on the wrapper itself.
        return getattr(self.__dict__["_jitted"], attr)


def instrument(name: str, jitted: Callable) -> Callable:
    """Wrap a jitted callable so its first enabled invocation registers
    the compiled program (see :class:`_InstrumentedProgram`)."""
    return _InstrumentedProgram(name, jitted)


def named_for_trace(name: str, fn: Callable) -> Callable:
    """Rename a PRE-jit function to its program name (sanitized —
    observe.xprof.sanitize is the one rule) so the XLA module lowers
    as ``jit_<program>`` and the profiler's ``hlo_module`` op tags
    attribute straight back to the registry name. Returns ``fn``."""
    from tensorflow_distributed_tpu.observe.xprof import sanitize

    fn.__name__ = sanitize(name)
    return fn


def instrument_jit(name: str, fn: Callable, **jit_kwargs) -> Callable:
    """``instrument(name, jax.jit(named_for_trace(name, fn), ...))`` —
    THE way a framework jit site registers: one name flows to the
    program registry, the compile record, the XLA module, and so the
    device-time attribution (observe/xprof.py)."""
    import jax

    return instrument(name, jax.jit(named_for_trace(name, fn),
                                    **jit_kwargs))


def _register_from(name: str, jitted: Callable, args, kwargs) -> None:
    """AOT lower+compile for the record; exceptions degrade to a
    null-field record (e.g. a non-jit callable, or an argument set the
    AOT path rejects) instead of propagating into the step."""
    try:
        lowered, compiled, lower_s, compile_s = aot_lower_compile(
            jitted, args, kwargs)
    except Exception as e:  # never take the run down for telemetry
        register_compiled(name, error=f"{type(e).__name__}: {e}")
        return
    register_compiled(name, lowered, compiled, lower_s=lower_s,
                      compile_s=compile_s)


def _latest_by_name() -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for rec in programs():
        out[rec["program"]] = rec
    return out


def hbm_budget() -> Optional[Dict[str, Any]]:
    """Process-level HBM budget rollup (latest record per program):
    how many programs are registered, the single largest resident
    program, and the sum over all of them (the worst case when
    executables stay loaded together, as the serving engine's do)."""
    latest = _latest_by_name()
    if not latest:
        return None
    peaks = [r["peak_hbm_bytes"] for r in latest.values()
             if r.get("peak_hbm_bytes") is not None]
    out: Dict[str, Any] = {"programs": len(latest)}
    if peaks:
        out["peak_hbm_bytes_max"] = max(peaks)
        out["peak_hbm_bytes_sum"] = sum(peaks)
    return out


def human_bytes(n: Optional[float]) -> str:
    """Byte counts for humans ("-" for null analyses) — the ONE
    formatter, shared with observe.report's Programs section."""
    if not isinstance(n, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def budget_table() -> str:
    """Human-readable HBM budget table over the registered programs
    (latest record per name), largest resident program first."""
    latest = _latest_by_name()
    if not latest:
        return ""
    rows = sorted(latest.values(),
                  key=lambda r: -(r.get("peak_hbm_bytes") or 0))
    lines = [f"{'program':<28} {'flops':>12} {'peak_hbm':>10} "
             f"{'donated':>10} {'compile_s':>9}"]
    for r in rows:
        flops = ("-" if r.get("flops") is None
                 else f"{r['flops']:.3g}")
        comp = ("-" if r.get("compile_s") is None
                else f"{r['compile_s']:.3f}")
        lines.append(
            f"{r['program']:<28} {flops:>12} "
            f"{human_bytes(r.get('peak_hbm_bytes')):>10} "
            f"{human_bytes(r.get('donated_bytes')):>10} {comp:>9}")
    budget = hbm_budget() or {}
    if "peak_hbm_bytes_sum" in budget:
        lines.append(
            f"{'TOTAL (all resident)':<28} {'':>12} "
            f"{human_bytes(budget['peak_hbm_bytes_sum']):>10}")
    return "\n".join(lines)
