"""Crash flight recorder: forensic state that survives the process.

A SIGKILL'd leg used to lose everything buffered since the last sink
flush; a SIGSEGV lost even its Python stacks. This module keeps a
bounded in-memory ring of the most recent full-fidelity observe
records (it rides the registry as just another sink) plus per-kind
tails of the records worth keeping longer than the ring (last
``compile`` / ``device_time`` / ``health`` / ``recovery`` /
``anomaly`` lines), and makes them durable two ways:

- **periodic snapshots**: every ``snapshot_every`` records — and
  IMMEDIATELY on every ``anomaly``/``recovery``/``postmortem`` record
  (the lines most likely to matter are never older than one event) —
  the whole ring is written atomically (tmp + fsync + rename) to
  ``flight-<pid>.jsonl``. A SIGKILL, which no handler can see,
  leaves this file as the leg's bundle.
- **postmortem dump**: on a trappable death — SIGTERM (the handler
  CHAINS to whatever was installed before, so the preemption guard's
  graceful drain still wins while the loop owns the signal), or a
  fatal exception (the Observatory dumps from ``close()`` when one is
  in flight: non-finite halt, recovery-budget exhaustion, stall) —
  a full bundle with the Python stacks of every live thread is
  written to ``postmortem-<pid>.jsonl``.

``faulthandler`` is enabled into ``faulthandler-<pid>.txt`` in the
same directory, so a hard fatal signal (SIGSEGV/SIGABRT — this
container's known XLA:CPU heap aborts included) at least leaves
native-crash stacks beside the last snapshot.

Bundle format: JSONL — a ``meta`` line (reason, signal, pid, git sha,
calibration id, config), one ``record`` line per ring entry, a
``tail`` line with the per-kind last records, and (dump only) a
``traceback`` line. Line-oriented on purpose: a write cut mid-line by
the death being recorded still yields every complete line
(:func:`load_bundle` counts-and-skips the torn tail). The postmortem
CLI (``python -m ...observe.postmortem <bundle>``) renders either
flavor into a human incident report.

Pure stdlib, import-light — the resilience supervisor imports
:func:`newest_bundle` to name a dead leg's bundle in its restart
events without touching any jax machinery.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, Iterator, Mapping, Optional

from tensorflow_distributed_tpu.utils.atomicio import atomic_write_jsonl

#: Record kinds kept in per-kind tails beyond the ring (the "last
#: known good" lines a postmortem wants even when the ring has churned
#: past them).
TAIL_KINDS = ("compile", "device_time", "health", "recovery",
              "anomaly", "slo_alert", "postmortem")

SNAPSHOT_PREFIX = "flight-"
BUNDLE_PREFIX = "postmortem-"
#: Record kinds that force an immediate snapshot (a kill right after
#: one of these must not lose it).
FLUSH_EVENTS = ("anomaly", "recovery", "postmortem", "slo_alert")


class FlightRecorder:
    """The per-process recorder. Build one, :meth:`install` the signal
    hooks, and feed it records — directly or via
    :class:`FlightRecorderSink` on the run's registry."""

    def __init__(self, directory: str, ring: int = 256,
                 snapshot_every: int = 50,
                 meta: Optional[Mapping[str, Any]] = None,
                 tail_per_kind: int = 16):
        if ring < 8:
            raise ValueError(f"flightrec ring must be >= 8, got {ring}")
        if snapshot_every < 1:
            raise ValueError(
                f"flightrec snapshot_every must be >= 1, "
                f"got {snapshot_every}")
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.ring: collections.deque = collections.deque(maxlen=ring)
        self.snapshot_every = int(snapshot_every)
        self.meta = dict(meta or {})
        self._tails: Dict[str, collections.deque] = {
            k: collections.deque(maxlen=tail_per_kind)
            for k in TAIL_KINDS}
        self._n = 0
        # Reentrant ON PURPOSE: the SIGTERM hook runs dump() on the
        # main thread, possibly interrupting a record()/snapshot()
        # that already holds the lock — a plain Lock would deadlock
        # the handler against the frame it interrupted.
        self._lock = threading.RLock()
        pid = os.getpid()
        self.snapshot_path = os.path.join(
            directory, f"{SNAPSHOT_PREFIX}{pid}.jsonl")
        self.bundle_path = os.path.join(
            directory, f"{BUNDLE_PREFIX}{pid}.jsonl")
        self.faulthandler_path = os.path.join(
            directory, f"faulthandler-{pid}.txt")
        self.dumped: Optional[str] = None
        self._fh_file = None
        self._fh_enabled = False
        self._prev_sigterm: Any = None
        self._installed_sigterm = False

    # -- lifecycle --------------------------------------------------------

    def install(self) -> None:
        """Arm the death hooks: faulthandler into the bundle dir for
        hard fatal signals, and a CHAINING SIGTERM hook (dump first,
        then the previous disposition — so a later-installed
        preemption guard that saves-and-restores handlers composes:
        while the guard owns the signal a SIGTERM is a graceful drain,
        not an incident; before and after, it dumps)."""
        try:
            self._fh_file = open(self.faulthandler_path, "w")
            faulthandler.enable(self._fh_file)
            self._fh_enabled = True
        except (OSError, ValueError):
            self._fh_file = None
        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM,
                                               self._on_sigterm)
            self._installed_sigterm = True
        except ValueError:
            pass  # not the main thread — snapshots still cover us

    def _on_sigterm(self, signum, frame) -> None:
        self.dump(reason="sigterm", signum=int(signum))
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev != signal.SIG_IGN:
            # SIG_DFL, or None (a disposition installed outside
            # Python, which we cannot invoke): preserve die-by-signal
            # semantics (the supervisor reads the -SIGTERM rc) —
            # restore the default and re-deliver rather than silently
            # absorbing the termination request. An explicit SIG_IGN
            # is respected.
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def close(self, final_snapshot: bool = True) -> None:
        """Disarm hooks (restoring the previous SIGTERM disposition
        when ours is still installed) and leave one final snapshot on
        disk. Idempotent."""
        if self._installed_sigterm:
            try:
                if signal.getsignal(signal.SIGTERM) == self._on_sigterm:
                    signal.signal(signal.SIGTERM, self._prev_sigterm)
            except (ValueError, TypeError):
                pass
            self._installed_sigterm = False
        if self._fh_enabled:
            try:
                faulthandler.disable()
            except Exception:
                pass
            self._fh_enabled = False
        if self._fh_file is not None:
            try:
                self._fh_file.close()
            except OSError:
                pass
            self._fh_file = None
        if final_snapshot:
            self.snapshot()

    # -- record flow ------------------------------------------------------

    def record(self, rec: Mapping[str, Any]) -> None:
        """One observe record into the ring (and its kind tail). Rings
        on every process; snapshots on the cadence and immediately on
        incident-class events."""
        rec = dict(rec)
        flush = rec.get("event") in FLUSH_EVENTS
        with self._lock:
            self.ring.append(rec)
            kind = rec.get("event")
            if kind in self._tails:
                self._tails[kind].append(rec)
            self._n += 1
            due = self._n % self.snapshot_every == 0
        if flush or due:
            self.snapshot()

    def _bundle_lines(self, kind: str, reason: Optional[str] = None,
                      signum: Optional[int] = None,
                      tracebacks: bool = False
                      ) -> Iterator[Dict[str, Any]]:
        with self._lock:
            ring = list(self.ring)
            tails = {k: list(v) for k, v in self._tails.items() if v}
        yield {
            "kind": "meta", "bundle": kind, "pid": os.getpid(),
            "written_t": round(time.time(), 3), "reason": reason,
            "signal": signum, "records": len(ring),
            "faulthandler": self.faulthandler_path, **self.meta,
        }
        for rec in ring:
            yield {"kind": "record", "data": rec}
        yield {"kind": "tail", "last": tails}
        if tracebacks:
            stacks = []
            frames = sys._current_frames()
            for thread in threading.enumerate():
                frame = frames.get(thread.ident)
                if frame is None:
                    continue
                stacks.append({
                    "thread": thread.name,
                    "stack": traceback.format_stack(frame)})
            yield {"kind": "traceback", "stacks": stacks}

    def snapshot(self) -> str:
        """Atomic ring snapshot (tmp + fsync + rename): the file a
        poller or a post-SIGKILL supervisor reads is always a complete
        bundle, never a torn write."""
        try:
            atomic_write_jsonl(self.snapshot_path,
                               self._bundle_lines("snapshot"),
                               default=str)
        except OSError:
            # Telemetry must never take down the run it observes.
            pass
        return self.snapshot_path

    def dump(self, reason: str, signum: Optional[int] = None
             ) -> Optional[str]:
        """The trappable-death bundle: full ring + tails + every live
        thread's Python stack, written straight through (per-line
        durability over atomicity — a death mid-dump still leaves
        every complete line, and :func:`load_bundle` tolerates the
        torn tail). First dump wins; later calls return its path."""
        if self.dumped is not None:
            return self.dumped
        try:
            # Straight-through on purpose (per-line durability over
            # atomicity): a death mid-dump still leaves every complete
            # line, and load_bundle tolerates the torn tail.
            # graftcheck: disable=raw-write-to-shared-path -- postmortem dump favors per-line durability over atomicity
            with open(self.bundle_path, "w") as f:
                for line in self._bundle_lines(
                        "postmortem", reason=reason, signum=signum,
                        tracebacks=True):
                    f.write(json.dumps(line, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            return None
        self.dumped = self.bundle_path
        return self.bundle_path


class FlightRecorderSink:
    """Registry-sink adapter: every emitted record flows into the
    recorder's ring; closing the sink leaves a final snapshot."""

    def __init__(self, recorder: FlightRecorder):
        self.recorder = recorder

    def emit(self, record: Mapping[str, Any]) -> None:
        self.recorder.record(record)

    def close(self) -> None:
        self.recorder.close()


# --- read side (postmortem CLI, supervisor, tests) ----------------------

def load_bundle(path: str) -> Dict[str, Any]:
    """Parse a bundle (snapshot or postmortem), tolerating a torn
    tail: a line cut mid-write by the death being recorded is counted
    in ``torn``, every complete line still loads. Returns
    ``{meta, records, last, tracebacks, torn, path}``."""
    out: Dict[str, Any] = {"meta": {}, "records": [], "last": {},
                           "tracebacks": [], "torn": 0, "path": path}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                out["torn"] += 1
                continue
            kind = obj.get("kind")
            if kind == "meta":
                out["meta"] = {k: v for k, v in obj.items()
                               if k != "kind"}
            elif kind == "record":
                out["records"].append(obj.get("data", {}))
            elif kind == "tail":
                out["last"] = obj.get("last", {})
            elif kind == "traceback":
                out["tracebacks"] = obj.get("stacks", [])
    return out


def newest_bundle(directory: str, since: float = 0.0
                  ) -> Optional[str]:
    """The dead leg's bundle: the newest ``postmortem-*.jsonl`` in
    ``directory`` modified at/after ``since``, falling back to the
    newest ``flight-*.jsonl`` snapshot (a SIGKILL writes no
    postmortem — the last snapshot IS the bundle). None when nothing
    qualifies; never raises (the supervisor calls this on its restart
    path)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    best: Dict[str, tuple] = {}
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        if name.startswith(BUNDLE_PREFIX):
            group = "postmortem"
        elif name.startswith(SNAPSHOT_PREFIX):
            group = "snapshot"
        else:
            continue
        path = os.path.join(directory, name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        if mtime < since:
            continue
        if group not in best or mtime > best[group][0]:
            best[group] = (mtime, path)
    if "postmortem" in best:
        return best["postmortem"][1]
    if "snapshot" in best:
        return best["snapshot"][1]
    return None
