"""Per-request distributed tracing for the serve path.

One serve run -> one Perfetto/Chrome trace file in which **every
request is an async span tree**: ``request`` (arrival -> retire) with
``queue`` (arrival -> admission), ``prefill`` (bucketed prefill +
row insert), and ``decode`` (first token -> last token) children, all
keyed by the request id so Perfetto renders each request on its own
track. Engine work lands as complete ("X") spans on the host thread —
``decode_step`` / ``verify_step`` batched per engine step (NOT per
token: a 10k-token run stays a few thousand events), ``prefill_b{n}``
and ``insert_row`` per admission — and the recovery/policy machinery
drops instant markers (``slot_quarantine``, ``weight_swap``,
``preempt``, ``journal_resume``, ``slo_alert``) exactly where they
happen, so a faulted run's recovery windows line up visually with the
requests they hit. Counter tracks (``slots``, ``queue``,
``tokens_per_s``, ``accept_rate``) give the run's shape at a glance.

Built on :class:`observe.trace.ChromeTracer`'s primitives (async
``b``/``e`` pairs, instants, counters). Open the file at
https://ui.perfetto.dev.

**Resume.** A journal-resumed serve leg (the PR-6 restart story) gets
``resume=True``: the dead leg's events are preloaded from the existing
file, its in-flight requests' unmatched async spans are CLOSED at the
resume instant (annotated ``process_death=True`` — that IS when they
stopped), and the new leg's clock starts after the old timeline, so
one file shows the whole faulted serve including the restart gap.

Every method is a no-op when disabled/unconfigured — the scheduler
and engine call unconditionally, like the training Observatory.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Dict, Iterator, Optional

from tensorflow_distributed_tpu.observe.trace import (
    ChromeTracer, load_trace, unbalanced_async)

_CAT = "serve"


class ServeTracer:
    """Request-tree + engine-span + counter recorder for a serve run."""

    def __init__(self, path: str = "", enabled: bool = True,
                 pid: int = 0, clock=time.perf_counter,
                 resume: bool = False, max_events: int = 200_000,
                 durable: bool = False):
        self.tracer = ChromeTracer(path, pid=pid, enabled=enabled,
                                   process_name="tfd-serve",
                                   clock=clock, max_events=max_events)
        self.enabled = self.tracer.enabled
        # durable=True flushes at every request-lifecycle edge
        # (admission, completion, eviction) instead of only on the 5s
        # cadence: a fleet replica can be SIGKILLed at any moment, and
        # the stitcher needs the moved request's spans ON DISK for the
        # failover to render — fleet runs are short and low-rate, so
        # the extra rewrites are cheap there (don't set it for a
        # 10k-request standalone serve).
        self.durable = bool(durable)
        self._open: Dict[str, set] = {}   # rid -> open child span names
        if self.enabled and resume and os.path.exists(path):
            try:
                prior = load_trace(path)
            except (OSError, ValueError, KeyError):
                prior = []   # unreadable tail from the kill — start fresh
            if prior:
                self.tracer.preload(prior)
                # The dead leg's in-flight spans end at process death;
                # close them HERE so the finished file's spans balance
                # (slobench gates exactly this) and Perfetto doesn't
                # stretch them to infinity.
                for ev in unbalanced_async(prior):
                    if ev.get("ph") != "b":
                        continue
                    self.tracer.async_end(ev["name"], ev.get("id"),
                                          cat=ev.get("cat", _CAT),
                                          process_death=True)
                self.instant("journal_resume", prior_events=len(prior))

    # -- request lifecycle (scheduler) ------------------------------------

    def request_queued(self, rid: int, slo: str = "standard",
                       prompt_len: int = 0, tenant: str = "") -> None:
        if not self.enabled:
            return
        args: Dict[str, Any] = {"slo": slo, "prompt_len": prompt_len}
        if tenant:
            args["tenant"] = tenant
        self.tracer.async_begin("request", rid, cat=_CAT, **args)
        self.tracer.async_begin("queue", rid, cat=_CAT)
        self._open[str(rid)] = {"request", "queue"}

    @contextlib.contextmanager
    def prefill(self, rid: int, bucket: int, slot: int
                ) -> Iterator[None]:
        """Admission: closes the queue span, wraps the prefill+insert
        in a ``prefill`` child, opens the ``decode`` span (the first
        token exists when prefill returns)."""
        if not self.enabled:
            yield
            return
        spans = self._open.setdefault(str(rid), {"request"})
        if "queue" in spans:
            self.tracer.async_end("queue", rid, cat=_CAT)
            spans.discard("queue")
        self.tracer.async_begin("prefill", rid, cat=_CAT,
                                bucket=bucket, slot=slot)
        try:
            yield
        finally:
            self.tracer.async_end("prefill", rid, cat=_CAT)
            self.tracer.async_begin("decode", rid, cat=_CAT)
            spans.add("decode")
            if self.durable:
                self.tracer.flush()

    def request_done(self, rid: int, finish: str, tokens: int,
                     ttft_ms: float) -> None:
        if not self.enabled:
            return
        spans = self._open.pop(str(rid), set())
        if "decode" in spans:
            self.tracer.async_end("decode", rid, cat=_CAT)
        self.tracer.async_end("request", rid, cat=_CAT, finish=finish,
                              tokens=tokens,
                              ttft_ms=round(ttft_ms, 3))
        if self.durable:
            self.tracer.flush()

    def request_evicted(self, rid: int, why: str) -> None:
        """Quarantine/preemption: the request leaves its slot and goes
        back to the queue as a continuation — close decode, reopen
        queue (same request id: one track shows serve -> evict ->
        requeue -> serve)."""
        if not self.enabled:
            return
        spans = self._open.setdefault(str(rid), {"request"})
        if "decode" in spans:
            self.tracer.async_end("decode", rid, cat=_CAT, why=why)
            spans.discard("decode")
        if "queue" not in spans:
            self.tracer.async_begin("queue", rid, cat=_CAT, why=why)
            spans.add("queue")
        if self.durable:
            self.tracer.flush()

    # -- engine + recovery ------------------------------------------------

    def engine_span(self, name: str, **args: Any):
        """Complete ("X") span for one engine dispatch (decode_step /
        verify_step / prefill_b{n} / insert_row) — decode ticks are
        batched per ENGINE STEP, one span covering every live slot."""
        if not self.enabled:
            return contextlib.nullcontext()
        return self.tracer.span(name, cat="serve_engine", **args)

    def instant(self, name: str, cat: str = "recovery",
                **args: Any) -> None:
        self.tracer.instant(name, cat=cat, **args)
        if cat == "recovery":
            # Recovery markers are rare and precious: a leg that dies
            # young (SIGKILL well inside the ChromeTracer's 5s flush
            # cadence) must still leave its quarantine/swap instants
            # on disk for the resumed leg to preload — the whole
            # point of the one-file-spans-the-restart story.
            self.tracer.flush()

    def counters(self, **values: float) -> None:
        """One counter sample per track name (slots / queue /
        tokens_per_s / accept_rate)."""
        if not self.enabled:
            return
        for name, value in values.items():
            self.tracer.counter(name, **{name: value})

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Close any spans still open (a crashed run's flush already
        wrote them; a clean close balances the file) and write."""
        if self.enabled:
            for rid, spans in list(self._open.items()):
                for name in ("decode", "queue"):
                    if name in spans:
                        self.tracer.async_end(name, rid, cat=_CAT)
                self.tracer.async_end("request", rid, cat=_CAT,
                                      finish="open_at_close")
            self._open.clear()
        self.tracer.close()

    def flush(self) -> None:
        self.tracer.flush()


def null_serve_tracer() -> ServeTracer:
    """A disabled tracer (no path) — call sites skip None checks."""
    return ServeTracer("", enabled=False)
