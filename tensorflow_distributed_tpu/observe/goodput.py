"""Goodput accounting: productive step time vs. everything else.

A preemptible-TPU fleet's real throughput isn't steps/sec inside the
steady loop — it's the fraction of wall time spent making forward
progress once restores, checkpoint stalls, eval passes, and preemption
drains are charged. This module keeps that ledger.

Overhead accrues into named categories via ``account(category)``
context managers. Nested accounting charges the OUTERMOST category
only (a checkpoint taken inside a preemption drain is drain time, not
double-counted), per thread. Background checkpoint writer threads are
deliberately NOT accounted — overlapped IO costs no goodput; only the
main thread's blocked time does (train/checkpoint.py wraps exactly
those portions).

The loop-facing object is :class:`GoodputCounter`; ``train.checkpoint``
and ``train.preemption`` reach the live one through the module-level
``set_active``/``account`` indirection so they stay importable (and
free) outside a training run.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional


class GoodputCounter:
    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._local = threading.local()
        self.overhead: Dict[str, float] = {}
        self.events: Dict[str, int] = {}
        self._t0 = clock()

    def add(self, category: str, seconds: float) -> None:
        with self._lock:
            self.overhead[category] = (
                self.overhead.get(category, 0.0) + seconds)

    def incr(self, event: str, n: int = 1) -> None:
        """Count a recovery event (rewind, ckpt_retry, quarantine,
        stall, skip) — the ledger's how-often companion to the
        how-long overhead categories; surfaces as ``<event>_count`` in
        :meth:`summary`."""
        with self._lock:
            self.events[event] = self.events.get(event, 0) + n

    @contextlib.contextmanager
    def account(self, category: str) -> Iterator[None]:
        """Charge the block's wall time to ``category`` unless already
        inside another accounted block on this thread."""
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        start = self._clock()
        stack.append((category, start))
        try:
            yield
        finally:
            stack.pop()
            if not stack:
                self.add(category, self._clock() - start)

    def charged(self) -> float:
        """Total overhead seconds INCLUDING the elapsed portion of an
        open outermost block on the calling thread (account() only
        accrues at exit). Lets two snapshots bracket a window and
        difference to exactly the overhead charged within it — the
        preemption drain accounting (train.preemption) relies on this
        when the notice lands mid-eval or mid-checkpoint.

        DELIBERATELY LOCK-FREE: the preemption SIGTERM handler calls
        this on the main thread, which may have been interrupted while
        holding self._lock (e.g. mid-add) — acquiring the non-
        reentrant lock there would deadlock. All accounting happens on
        the main thread (background writer IO is unaccounted by
        design), so a bare read between bytecodes is consistent under
        the GIL."""
        total = sum(self.overhead.values())
        stack = getattr(self._local, "stack", None)
        if stack:
            total += self._clock() - stack[0][1]
        return total

    def summary(self, total_seconds: Optional[float] = None
                ) -> Dict[str, float]:
        """Goodput fraction over ``total_seconds`` (default: since the
        counter was created): productive = total - accounted overhead."""
        total = (total_seconds if total_seconds is not None
                 else self._clock() - self._t0)
        with self._lock:
            overhead = dict(self.overhead)
            events = dict(self.events)
        spent = sum(overhead.values())
        productive = max(total - spent, 0.0)
        out = {f"{k}_seconds": round(v, 4) for k, v in overhead.items()}
        out.update({f"{k}_count": v for k, v in events.items()})
        out["total_seconds"] = round(total, 4)
        out["productive_seconds"] = round(productive, 4)
        out["goodput"] = round(productive / total, 4) if total > 0 else 0.0
        return out


# --- module-level indirection (train.checkpoint / train.preemption) -----

_active: Optional[GoodputCounter] = None


def set_active(counter: Optional[GoodputCounter]) -> None:
    """Install the run's counter (the train loop does; tests may)."""
    global _active
    _active = counter


def get_active() -> Optional[GoodputCounter]:
    return _active


@contextlib.contextmanager
def account(category: str) -> Iterator[None]:
    """Charge to the active counter; no-op when none is installed."""
    counter = _active
    if counter is None:
        yield
        return
    with counter.account(category):
        yield


def add(category: str, seconds: float) -> None:
    counter = _active
    if counter is not None and seconds > 0:
        counter.add(category, seconds)


def incr(event: str, n: int = 1) -> None:
    counter = _active
    if counter is not None:
        counter.incr(event, n)


def accounted(category: str):
    """Decorator form of :func:`account` — charges the wrapped call's
    wall time to ``category`` on the active counter (no-op without
    one). train.checkpoint uses it on its main-thread blocking entry
    points."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with account(category):
                return fn(*args, **kwargs)
        return wrapper

    return deco
