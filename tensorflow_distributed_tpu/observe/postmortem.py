"""Postmortem forensics: a human incident report from a flight-recorder
bundle.

::

    python -m tensorflow_distributed_tpu.observe.postmortem \\
        /path/to/postmortem-<pid>.jsonl [--timeline N] [--json]

Accepts either bundle flavor (``postmortem-*.jsonl`` — a trapped
death's full dump — or ``flight-*.jsonl`` — the last periodic snapshot
a SIGKILL left behind; observe/flightrec.py) and renders:

- the death: reason / signal / pid / written-at, with provenance
  (git sha, calibration id, config hash);
- the anomalies that preceded it (observe/anomaly.py records from the
  bundle's tail), newest last;
- a **likely-cause heuristic** — one sentence connecting the last
  anomaly to the death ("grad-norm explosion at step 38 preceded
  nonfinite halt at step 40");
- the timeline: the last N ring records around the death;
- the per-kind tails (last compile / device_time / health / recovery
  lines) and captured thread stacks.

Pure stdlib, read-only — safe to run on a live run's snapshot.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from tensorflow_distributed_tpu.observe.flightrec import load_bundle

#: detector id -> human phrase for the likely-cause sentence.
DETECTOR_PHRASES = {
    "loss_nonfinite": "non-finite loss",
    "loss_spike": "loss spike",
    "loss_plateau": "loss plateau",
    "step_time_spike": "step-time spike",
    "throughput_slope": "throughput degradation",
    "grad_norm_spike": "grad-norm explosion",
    "update_ratio_collapse": "update-ratio collapse",
    "ttft_spike": "TTFT spike",
    "decode_time_spike": "decode-step-time spike",
    "queue_growth": "queue growth",
    "slot_nonfinite": "slot non-finite logits",
}


def _phrase(detector: str) -> str:
    base = detector.split("/", 1)[0]
    phrase = DETECTOR_PHRASES.get(base, base.replace("_", " "))
    if "/" in detector:
        phrase += f" in {detector.split('/', 1)[1]}"
    return phrase


def _anomalies(bundle: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Anomaly records, tail-preferred (the tail outlives the ring),
    deduped against ring copies, oldest first."""
    seen = set()
    out: List[Dict[str, Any]] = []
    for rec in (bundle.get("last", {}).get("anomaly", [])
                + [r for r in bundle.get("records", [])
                   if r.get("event") == "anomaly"]):
        key = (rec.get("detector"), rec.get("step"), rec.get("t"))
        if key in seen:
            continue
        seen.add(key)
        out.append(rec)
    out.sort(key=lambda r: (r.get("step", 0), r.get("t", 0.0)))
    return out


def _death_step(bundle: Dict[str, Any]) -> Optional[int]:
    steps = [r.get("step") for r in bundle.get("records", [])
             if isinstance(r.get("step"), int)]
    return max(steps) if steps else None


def likely_cause(bundle: Dict[str, Any]) -> str:
    """The one-sentence heuristic: connect the last pre-death anomaly
    (when one exists) to how the process died."""
    meta = bundle.get("meta", {})
    reason = str(meta.get("reason") or "")
    anoms = _anomalies(bundle)
    last = anoms[-1] if anoms else None
    death = _death_step(bundle)
    at = f" at step {death}" if death is not None else ""
    injected = sorted({str(r.get("fault")) for r in
                       bundle.get("last", {}).get("recovery", [])
                       if r.get("kind") == "fault_injected"
                       and r.get("fault")})
    suffix = (f" (injected faults on record: {', '.join(injected)})"
              if injected else "")

    def _preceded(what: str) -> str:
        if last is None:
            return (f"no anomalies preceded the {what}{at}"
                    f"{suffix}")
        return (f"{_phrase(str(last.get('detector')))} at step "
                f"{last.get('step')} preceded the {what}{at}{suffix}")

    low = reason.lower()
    if ("floatingpointerror" in low or "non-finite" in low
            or "recoverybudgetexceeded" in low):
        return _preceded("nonfinite halt")
    if "stallerror" in low or "stalled" in low:
        return _preceded("stall halt")
    if "sigterm" in low or meta.get("signal"):
        return _preceded("termination")
    if meta.get("bundle") == "snapshot":
        # No trapped death wrote this — the process was killed
        # outright (SIGKILL / OOM) and the last snapshot is what
        # survived.
        return _preceded("untrapped process death")
    return _preceded("process death")


def _fmt_record(rec: Dict[str, Any]) -> str:
    event = rec.get("event", "?")
    bits = [f"t={rec['t']:.3f}" if isinstance(rec.get("t"), (int, float))
            else "t=?"]
    if "step" in rec:
        bits.append(f"step={rec['step']}")
    bits.append(f"event={event}")
    for key in ("detector", "severity", "kind", "fault", "module",
                "loss", "value", "baseline", "rid", "slot"):
        if key in rec:
            val = rec[key]
            bits.append(f"{key}={val:.6g}"
                        if isinstance(val, float) else f"{key}={val}")
    return " ".join(bits)


def report(bundle: Dict[str, Any], timeline: int = 12) -> str:
    meta = bundle.get("meta", {})
    lines = [f"== postmortem: {bundle.get('path', '?')}"]
    head = [f"bundle={meta.get('bundle', '?')}",
            f"pid={meta.get('pid', '?')}"]
    if meta.get("reason"):
        head.append(f"reason={meta['reason']}")
    if meta.get("signal"):
        head.append(f"signal={meta['signal']}")
    lines.append("  " + " ".join(head))
    prov = [f"{k}={meta[k]}" for k in
            ("git_sha", "calibration_id", "config_hash", "mesh")
            if meta.get(k) is not None]
    if prov:
        lines.append("  " + " ".join(prov))
    if bundle.get("torn"):
        lines.append(f"  torn_lines={bundle['torn']} (tolerated — the "
                     f"death cut the final write)")
    anoms = _anomalies(bundle)
    lines.append(f"Anomalies preceding death ({len(anoms)})")
    for rec in anoms[-8:]:
        lines.append(
            f"  [step {rec.get('step', '?')}] "
            f"{rec.get('detector', '?')} "
            f"severity={rec.get('severity', '?')}"
            + (f" value={rec['value']}" if "value" in rec else "")
            + (f" baseline={rec['baseline']}"
               if "baseline" in rec else ""))
    lines.append("Likely cause")
    lines.append(f"  {likely_cause(bundle)}")
    records = bundle.get("records", [])
    lines.append(f"Timeline (last {min(timeline, len(records))} of "
                 f"{len(records)} ring records)")
    for rec in records[-timeline:]:
        lines.append("  " + _fmt_record(rec))
    tails = bundle.get("last", {})
    if tails:
        lines.append("Last by kind")
        lines.append("  " + " ".join(
            f"{kind}={len(recs)}" for kind, recs
            in sorted(tails.items()) if recs))
    if bundle.get("tracebacks"):
        lines.append(f"Tracebacks ({len(bundle['tracebacks'])} "
                     f"threads captured)")
        for tb in bundle["tracebacks"]:
            stack = tb.get("stack") or []
            tail = stack[-1].strip().splitlines()[0] if stack else "?"
            lines.append(f"  {tb.get('thread', '?')}: {tail}")
    if meta.get("faulthandler"):
        lines.append(f"faulthandler: {meta['faulthandler']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tensorflow_distributed_tpu.observe.postmortem",
        description="render a flight-recorder bundle as a human "
                    "incident report")
    parser.add_argument("bundle", help="postmortem-*.jsonl or "
                        "flight-*.jsonl bundle path")
    parser.add_argument("--timeline", type=int, default=12,
                        help="ring records to show around the death")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable: the parsed bundle + "
                        "likely_cause")
    args = parser.parse_args(argv)
    try:
        bundle = load_bundle(args.bundle)
    except OSError as e:
        print(f"postmortem: {e}", file=sys.stderr)
        return 1
    if not bundle["meta"] and not bundle["records"]:
        print(f"postmortem: {args.bundle}: not a flight-recorder "
              f"bundle (no meta/record lines)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({**bundle,
                          "likely_cause": likely_cause(bundle)},
                         default=str))
    else:
        print(report(bundle, timeline=args.timeline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
