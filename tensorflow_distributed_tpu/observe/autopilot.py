"""Autopilot: close the calibrate→plan→act loop on the run's own telemetry.

Every half of a self-tuning loop already exists as a separate artifact
in this repo — the AOT planner picks layouts (analysis/planner), the
calibration fitter turns measured steps into effective device rates
(planner/calibrate.py), anomaly detection watches live behavior
(observe/anomaly.py), the SLO monitor burns error budget on the
deterministic decode-step clock (observe/slo.py), and the scheduler
accepts live control commands between decode steps (serve/scheduler.py
``feed_cmd``). A human still read the telemetry and turned the knobs.
This module is the missing controller: it subscribes to the streams the
run already emits and closes four concrete loops against existing
actuators:

1. **Calibration** — join each run's ``compile`` × ``device_time``
   records (the same join ``calibrate.samples_from_metrics`` does on a
   finished artifact, here done streaming) and refit the effective-rate
   profile when the plan's predicted→measured drift leaves tolerance
   (``plan_drift.drift_ratio``, or the per-program measured/predicted
   ratios when no drift record exists). The refit writes an atomic
   ``calibration.json`` (``--observe.autopilot-calibration``) and an
   optional ``replan`` hook re-runs the planner against it.
2. **Capacity** — generalize the PR-15 one-shot ``auto_num_pages``
   sizer into a feedback rule: sustained page-pool pressure shrinks the
   scheduler's *effective slot cap* (fewer live slots pin fewer pages);
   sustained headroom grows it back toward ``num_slots``. The
   boot-time knobs it cannot change live (``--serve.num-pages``, the
   bucket ladder) get auditable *advisory* recommendations at run end,
   sized from the observed ``slot_pages_peak`` and the prompt-length
   distribution.
3. **Speculation** — walk the draft depth ``k`` along a bounded ladder
   from the rolling-window accept rate: a workload that accepts almost
   everything earns a deeper draft; one that rejects almost everything
   pays for k it never cashes. Verify programs compile lazily per
   (model, k), and greedy verify is token-identical at any k by
   construction, so the actuation is stream-safe.
4. **Admission** — drive the scheduler's admission threshold
   (``decode_priority``) from SLO burn: sustained alerting halves it
   (queued requests admit sooner — TTFT is what burns), sustained calm
   relaxes it back toward the configured baseline one step at a time
   (AIMD, so a knob that *caused* burn is re-approached slowly, not
   snapped back to).

Every actuation is a ``{"cmd": "tune", ...}`` command routed through
the scheduler's existing control-command path — the same path fleet
drain/swap/cancel commands take — so it applies between decode steps
and token identity is preserved by construction (greedy determinism +
continuation semantics; TUNEBENCH gates the streams stay identical
across every live actuation). Every decision emits one auditable
``tune`` record carrying machine-readable evidence: the signal, the
observed value, the threshold it crossed, and the triggering context.

Decisions are **hysteretic and rate-limited** so a well-tuned run stays
decision-quiet: a trigger must hold for ``confirm`` consecutive
evaluations (deadbands between the raise/lower thresholds absorb
noise), each knob then cools down for ``cooldown`` decode steps, and at
most one knob actuates per evaluation tick. Knobs named in
``--observe.autopilot-pin`` are never touched.

Pure stdlib on purpose: the controller must import (and unit-test) on
a box with no jax. The calibration fitter (already stdlib) is the only
repo import, done lazily at refit time.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

#: every knob the autopilot can touch — the valid ``autopilot_pin``
#: vocabulary (config.py validates against this).
KNOBS = ("calibration", "slot_cap", "spec_k", "decode_priority",
         "num_pages", "buckets")

#: loop-2 pool-occupancy deadband: shrink the slot cap above HI,
#: grow it back below LO, stay quiet in between.
POOL_HI, POOL_LO = 0.92, 0.60
#: loop-3 accept-rate deadband: deepen the draft above HI, shallow it
#: below LO.
ACCEPT_HI, ACCEPT_LO = 0.75, 0.35
#: loop-2 advisory band: recommend a different --serve.num-pages only
#: when the observed-peak sizing moves the pool by more than this.
PAGES_REL_TOL = 0.2


def _round(v):
    return round(v, 6) if isinstance(v, float) else v


class Autopilot:
    """The online controller. Owned by the serve observatory
    (observe/hub.py builds it from the ``--observe.autopilot*`` knobs)
    and driven by the scheduler on the decode-step clock:
    :meth:`maybe_step` returns the ``tune`` commands to route through
    ``feed_cmd``. None-safe like every other scheduler hook — a run
    without ``--observe.autopilot`` never constructs one."""

    def __init__(self, emit: Optional[Callable[..., None]] = None, *,
                 every: int = 50, confirm: int = 3, cooldown: int = 200,
                 drift_tol: float = 0.25,
                 pins: Sequence[str] = (),
                 metrics_path: str = "",
                 calibration_path: str = "",
                 k_ladder: Sequence[int] = (1, 2, 4, 8),
                 replan: Optional[Callable[[dict], None]] = None):
        if every < 1:
            raise ValueError(f"autopilot every must be >= 1, got {every}")
        if confirm < 1:
            raise ValueError(
                f"autopilot confirm must be >= 1, got {confirm}")
        if cooldown < 0:
            raise ValueError(
                f"autopilot cooldown must be >= 0, got {cooldown}")
        if drift_tol <= 0:
            raise ValueError(
                f"autopilot drift_tol must be > 0, got {drift_tol}")
        bad = sorted(set(pins) - set(KNOBS))
        if bad:
            raise ValueError(
                f"unknown autopilot pin knob(s) {', '.join(bad)} "
                f"(valid: {', '.join(KNOBS)})")
        self.emit = emit
        self.every = int(every)
        self.confirm = int(confirm)
        self.cooldown = int(cooldown)
        self.drift_tol = float(drift_tol)
        self.pins = frozenset(pins)
        self.metrics_path = metrics_path
        self.calibration_path = calibration_path
        self.k_ladder = tuple(sorted(set(int(k) for k in k_ladder)))
        if not self.k_ladder or self.k_ladder[0] < 1:
            raise ValueError(
                f"autopilot k_ladder must be positive ints, got "
                f"{k_ladder!r}")
        self.replan = replan
        # -- decision bookkeeping (the tune_summary rollup) ----------
        self.actions = 0          # applied knob changes
        self.advisories = 0       # applied=False recommendations
        self.evals = 0
        self.suppressed = 0       # triggered but cooling down
        self.by_knob: Dict[str, int] = {}
        self._confirm: Dict[str, int] = {}
        self._cool: Dict[str, int] = {}
        # -- bound run context (scheduler/run.py fill these in) ------
        self._num_slots = 0
        self._slot_cap = 0
        self._spec_k = 0
        self._has_spec = False
        self._dp0 = 0             # configured decode_priority baseline
        self._dp = 0
        self._num_pages = 0
        self._recommend_pages: Optional[Callable[[int], tuple]] = None
        self._buckets: tuple = ()
        self._prompt_lens: List[int] = []
        # -- loop-1 streaming state ----------------------------------
        self._tail_pos = 0
        self._costs: Dict[str, dict] = {}      # program -> compile rec
        self._measured: Dict[str, dict] = {}   # program -> device_time
        self._drift: Optional[dict] = None     # latest plan_drift rec
        self._drift_seen = 0      # drift-evidence records at last refit
        self._drift_new = 0       # drift-evidence records seen so far

    # -- run-context binding ---------------------------------------------

    def bind_scheduler(self, *, num_slots: int = 0, spec_k: int = 0,
                       decode_priority: int = 8,
                       has_spec: bool = False) -> None:
        """Called by the Scheduler ctor: the initial knob values the
        feedback rules move relative to."""
        self._num_slots = int(num_slots)
        self._slot_cap = int(num_slots)
        self._spec_k = int(spec_k)
        self._has_spec = bool(has_spec) and spec_k > 0
        self._dp0 = self._dp = int(decode_priority)

    def bind_paging(self, *, num_pages: int = 0,
                    recommend: Optional[Callable[[int], tuple]] = None
                    ) -> None:
        """serve/run.py hands over the boot-time sizing context: the
        pool it allocated and a closure over ``auto_num_pages`` (the
        PR-15 one-shot sizer) that re-sizes from an observed peak —
        the autopilot stays jax-free and never re-derives page bytes."""
        self._num_pages = int(num_pages)
        self._recommend_pages = recommend

    def bind_buckets(self, buckets: Sequence[int]) -> None:
        self._buckets = tuple(int(b) for b in buckets)

    def observe_prompt(self, prompt_len: int) -> None:
        """One host int per admission — the prompt-length distribution
        the bucket/num-pages recommendations are sized from."""
        self._prompt_lens.append(int(prompt_len))

    # -- record intake (loop 1) ------------------------------------------

    def observe_record(self, kind: str, rec: Dict[str, Any]) -> None:
        """Streamed telemetry intake: the compile × device_time join
        and the plan-drift signal. Fed by :meth:`_tail` from the run's
        own metrics JSONL (the streams the run already emits), or
        directly by tests."""
        if kind == "compile" and rec.get("program"):
            self._costs[rec["program"]] = rec
        elif kind == "device_time" and rec.get("program") and isinstance(
                rec.get("device_ms_per_call"), (int, float)):
            self._measured[rec["program"]] = rec
            self._drift_new += 1
        elif kind == "plan_drift" and isinstance(
                rec.get("drift_ratio"), (int, float)):
            self._drift = rec
            self._drift_new += 1

    def _tail(self) -> None:
        """Incrementally read NEW lines from the run's metrics JSONL
        (the registry's JSONL sink flushes per record). Count-and-skip
        on torn tails, same as observe.report."""
        if not self.metrics_path:
            return
        try:
            size = os.path.getsize(self.metrics_path)
        except OSError:
            return
        if size <= self._tail_pos:
            return
        try:
            with open(self.metrics_path) as f:
                f.seek(self._tail_pos)
                chunk = f.read()
        except OSError:
            return
        # Only consume complete lines; a mid-write tail stays for the
        # next tick.
        last_nl = chunk.rfind("\n")
        if last_nl < 0:
            return
        self._tail_pos += last_nl + 1
        for line in chunk[:last_nl].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and rec.get("event"):
                self.observe_record(str(rec["event"]), rec)

    # -- hysteresis helpers ----------------------------------------------

    def _sustained(self, key: str, cond: bool) -> bool:
        """Confirm-count hysteresis: a trigger must hold for
        ``confirm`` consecutive evaluations. Any tick off-trigger
        resets the count — a noisy-but-healthy stream never acts."""
        if cond:
            self._confirm[key] = self._confirm.get(key, 0) + 1
        else:
            self._confirm[key] = 0
        return self._confirm[key] >= self.confirm

    def _ready(self, knob: str, step: int) -> bool:
        last = self._cool.get(knob)
        return last is None or step - last >= self.cooldown

    def _fire(self, step: int, *, loop: str, knob: str, action: str,
              value, prev, signal: str, observed, threshold,
              applied: bool, evidence: Optional[dict] = None
              ) -> Optional[dict]:
        """Record one decision (auditable ``tune`` record with the
        triggering signal + threshold) and return the control command
        for an applied actuation (None for advisories)."""
        self._cool[knob] = step
        self._confirm.pop(f"{knob}:{action}", None)
        if applied:
            self.actions += 1
            self.by_knob[knob] = self.by_knob.get(knob, 0) + 1
        else:
            self.advisories += 1
        if self.emit is not None:
            self.emit("tune", step=int(step), loop=loop, knob=knob,
                      action=action, value=value, prev=prev,
                      signal=signal, observed=_round(observed),
                      threshold=_round(threshold), applied=applied,
                      evidence=evidence or {})
        if not applied:
            return None
        return {"cmd": "tune", "knob": knob, "value": value}

    # -- the four loops ---------------------------------------------------

    def _loop_admission(self, step: int, snap: Dict[str, Any]
                        ) -> Optional[dict]:
        """Loop 4: SLO burn drives the admission threshold. AIMD on
        ``decode_priority``: sustained alerting halves it (admit
        waiting requests sooner — queue time is what burns TTFT),
        sustained calm adds 1 back toward the configured baseline."""
        if "decode_priority" in self.pins:
            return None
        slo = snap.get("slo")
        if not isinstance(slo, dict):
            return None
        # SLOMonitor.snapshot() is keyed by target:
        # {"ttft_p95": {"alerting": ..., "burn_fast": ...}, ...}
        entries = {k: e for k, e in slo.items() if isinstance(e, dict)}
        firing = sorted(k for k, e in entries.items()
                        if e.get("alerting"))
        alerting = bool(firing)
        burns = {k: e.get("burn_fast") for k, e in entries.items()}
        worst = max((v for v in burns.values()
                     if isinstance(v, (int, float))), default=0.0)
        if self._sustained("decode_priority:tighten",
                           alerting) and self._dp > 1:
            if not self._ready("decode_priority", step):
                self.suppressed += 1
                return None
            prev, self._dp = self._dp, max(1, self._dp // 2)
            return self._fire(
                step, loop="admission", knob="decode_priority",
                action="tighten", value=self._dp, prev=prev,
                signal="slo_burn_fast", observed=worst, threshold=1.0,
                applied=True,
                evidence={"alerting": firing, "burn_fast": burns})
        if self._sustained("decode_priority:relax",
                           not alerting and self._dp < self._dp0):
            if not self._ready("decode_priority", step):
                self.suppressed += 1
                return None
            prev, self._dp = self._dp, min(self._dp0, self._dp + 1)
            return self._fire(
                step, loop="admission", knob="decode_priority",
                action="relax", value=self._dp, prev=prev,
                signal="slo_burn_fast", observed=worst, threshold=1.0,
                applied=True, evidence={"baseline": self._dp0})
        return None

    def _loop_capacity(self, step: int, snap: Dict[str, Any]
                       ) -> Optional[dict]:
        """Loop 2 (live half): page-pool pressure ⇄ effective slot
        cap. Fewer live slots pin fewer pages; headroom grows the cap
        back toward the allocated ``num_slots``."""
        if "slot_cap" in self.pins:
            return None
        occ = snap.get("pool_occupancy")
        if not isinstance(occ, (int, float)) or self._num_slots < 2:
            return None
        if self._sustained("slot_cap:shrink",
                           occ >= POOL_HI) and self._slot_cap > 1:
            if not self._ready("slot_cap", step):
                self.suppressed += 1
                return None
            prev, self._slot_cap = self._slot_cap, self._slot_cap - 1
            return self._fire(
                step, loop="capacity", knob="slot_cap",
                action="shrink", value=self._slot_cap, prev=prev,
                signal="pool_occupancy", observed=occ,
                threshold=POOL_HI, applied=True,
                evidence={"num_pages": snap.get("num_pages"),
                          "pages_in_use": snap.get("pages_in_use"),
                          "slot_pages_peak":
                              snap.get("slot_pages_peak")})
        if self._sustained(
                "slot_cap:grow",
                occ <= POOL_LO) and self._slot_cap < self._num_slots:
            if not self._ready("slot_cap", step):
                self.suppressed += 1
                return None
            prev, self._slot_cap = self._slot_cap, self._slot_cap + 1
            return self._fire(
                step, loop="capacity", knob="slot_cap", action="grow",
                value=self._slot_cap, prev=prev,
                signal="pool_occupancy", observed=occ,
                threshold=POOL_LO, applied=True,
                evidence={"num_slots": self._num_slots})
        return None

    def _loop_speculation(self, step: int, snap: Dict[str, Any]
                          ) -> Optional[dict]:
        """Loop 3: draft depth k from the rolling accept rate, one
        ladder rung at a time."""
        if "spec_k" in self.pins or not self._has_spec:
            return None
        ar = snap.get("accept_rate_window",
                      snap.get("accept_rate"))
        if not isinstance(ar, (int, float)):
            return None
        ladder = self.k_ladder
        try:
            i = ladder.index(self._spec_k)
        except ValueError:
            # Configured k off-ladder: adopt the nearest rung below
            # (or the bottom) as the anchor without actuating.
            i = max((j for j, k in enumerate(ladder)
                     if k <= self._spec_k), default=0)
        if self._sustained("spec_k:deepen",
                           ar >= ACCEPT_HI) and i + 1 < len(ladder):
            if not self._ready("spec_k", step):
                self.suppressed += 1
                return None
            prev, self._spec_k = self._spec_k, ladder[i + 1]
            return self._fire(
                step, loop="speculation", knob="spec_k",
                action="deepen", value=self._spec_k, prev=prev,
                signal="accept_rate_window", observed=ar,
                threshold=ACCEPT_HI, applied=True,
                evidence={"ladder": list(ladder)})
        if self._sustained("spec_k:shallow", ar <= ACCEPT_LO) and i > 0:
            if not self._ready("spec_k", step):
                self.suppressed += 1
                return None
            prev, self._spec_k = self._spec_k, ladder[i - 1]
            return self._fire(
                step, loop="speculation", knob="spec_k",
                action="shallow", value=self._spec_k, prev=prev,
                signal="accept_rate_window", observed=ar,
                threshold=ACCEPT_LO, applied=True,
                evidence={"ladder": list(ladder)})
        return None

    def _drift_evidence(self) -> Optional[dict]:
        """The trigger signal for a refit: the run's own ``plan_drift``
        record when one landed, else the median measured/predicted
        ratio across the device_time attributions."""
        if self._drift is not None:
            return {"source": "plan_drift",
                    "drift_ratio": float(self._drift["drift_ratio"]),
                    "record": {k: self._drift.get(k) for k in
                               ("predicted_step_ms",
                                "measured_step_ms_p50",
                                "drift_ratio", "calibration_id")}}
        ratios = []
        for prog, rec in self._measured.items():
            m = rec.get("device_ms_per_call")
            p = rec.get("predicted_ms_per_call")
            if isinstance(m, (int, float)) and isinstance(
                    p, (int, float)) and p > 0:
                ratios.append(m / p)
        if not ratios:
            return None
        ratios.sort()
        med = ratios[len(ratios) // 2]
        return {"source": "device_time", "drift_ratio": med,
                "programs": len(ratios)}

    def _loop_calibration(self, step: int) -> Optional[dict]:
        """Loop 1: refit the effective-rate profile from the streaming
        compile × device_time join when drift leaves tolerance. No
        confirm count — the drift signal is already an aggregate over
        a measurement window, not per-step noise — but evidence-gated:
        a refit consumes the records that justified it, and the loop
        stays quiet until NEW measurements land."""
        if "calibration" in self.pins:
            return None
        if self._drift_new <= self._drift_seen:
            return None
        ev = self._drift_evidence()
        if ev is None or abs(ev["drift_ratio"] - 1.0) <= self.drift_tol:
            return None
        samples = [
            {"flops": c.get("flops"),
             "bytes_accessed": c.get("bytes_accessed"),
             "collective_bytes": 0.0,
             "measured_ms": self._measured[p].get("device_ms_per_call"),
             "key": p}
            for p, c in self._costs.items() if p in self._measured]
        if len(samples) < 2:
            return None
        if not self._ready("calibration", step):
            self.suppressed += 1
            return None
        from tensorflow_distributed_tpu.analysis.planner import (
            calibrate)
        try:
            fit = calibrate.fit_rates(samples)
        except ValueError:
            return None
        self._drift_seen = self._drift_new
        profile = calibrate.make_profile(
            fit, platform="autopilot", device_kind="measured",
            source=f"autopilot:{os.path.basename(self.metrics_path)}"
                   if self.metrics_path else "autopilot:stream")
        applied = bool(self.calibration_path)
        if applied:
            calibrate.write_calibration(profile,
                                        self.calibration_path)
        if self.replan is not None:
            self.replan(profile)
        self._fire(
            step, loop="calibration", knob="calibration",
            action="refit", value=profile["calibration_id"],
            prev=ev.get("record", {}).get("calibration_id"),
            signal="drift_ratio", observed=ev["drift_ratio"],
            threshold=1.0 + self.drift_tol, applied=applied,
            evidence={**ev, "samples": fit["samples"],
                      "median_abs_rel_err":
                          fit["median_abs_rel_err"],
                      "path": self.calibration_path or None})
        # A calibration refit is a file write + optional replan, not a
        # scheduler knob — nothing to route through feed_cmd.
        return None

    # -- the scheduler-facing hook ----------------------------------------

    def maybe_step(self, step: int,
                   snap_fn: Callable[[], Dict[str, Any]]
                   ) -> List[dict]:
        """Called by the scheduler every decode step; evaluates on the
        ``every`` cadence (``snap_fn`` is only invoked then — the off-
        cadence cost is one modulo). Returns the ``tune`` commands to
        route through ``feed_cmd``."""
        if step % self.every != 0:
            return []
        return self.evaluate(step, snap_fn())

    def evaluate(self, step: int, snap: Dict[str, Any]) -> List[dict]:
        """One evaluation tick over a metrics snapshot. At most ONE
        applied actuation per tick (the rate limit on top of per-knob
        cooldowns): loops are consulted in protection order —
        admission (SLO), capacity, speculation — and calibration
        (a file write, not a scheduler command) runs independently."""
        self.evals += 1
        self._tail()
        cmds: List[dict] = []
        for loop in (self._loop_admission, self._loop_capacity,
                     self._loop_speculation):
            cmd = loop(step, snap)
            if cmd is not None:
                cmds.append(cmd)
                break
        self._loop_calibration(step)
        return cmds

    # -- run-end rollup ----------------------------------------------------

    def _recommendations(self, snap: Dict[str, Any], step: int) -> None:
        """The boot-time knobs (advisory half of loop 2): re-run the
        one-shot sizer against the MEASURED peak, and size the bucket
        ladder's top to the observed prompt distribution."""
        peak = snap.get("slot_pages_peak")
        if ("num_pages" not in self.pins and self._num_pages
                and self._recommend_pages is not None
                and isinstance(peak, (int, float)) and peak > 0):
            rec_pages, lines = self._recommend_pages(int(peak))
            if (abs(rec_pages - self._num_pages)
                    > PAGES_REL_TOL * self._num_pages):
                self._fire(
                    step, loop="capacity", knob="num_pages",
                    action="recommend", value=int(rec_pages),
                    prev=self._num_pages, signal="slot_pages_peak",
                    observed=peak,
                    threshold=PAGES_REL_TOL, applied=False,
                    evidence={"rationale": list(lines)})
        if ("buckets" not in self.pins and self._buckets
                and self._prompt_lens):
            lens = sorted(self._prompt_lens)
            p99 = lens[min(len(lens) - 1,
                           int(0.99 * (len(lens) - 1)))]
            top = max(self._buckets)
            need = 1
            while need < p99:
                need *= 2
            if need != top:
                self._fire(
                    step, loop="capacity", knob="buckets",
                    action="recommend", value=int(need), prev=top,
                    signal="prompt_len_p99", observed=p99,
                    threshold=float(top), applied=False,
                    evidence={"prompts": len(lens),
                              "buckets": list(self._buckets)})

    def emit_summary(self, step: int,
                     snap: Optional[Dict[str, Any]] = None) -> None:
        """One ``tune_summary`` at run end: the decision ledger rollup
        plus the advisory recommendations (quiet == zero applied
        actions — the control-run gate TUNEBENCH pins)."""
        if snap is not None:
            self._recommendations(snap, step)
        if self.emit is not None:
            self.emit("tune_summary", step=int(step),
                      evals=self.evals, actions=self.actions,
                      advisories=self.advisories,
                      suppressed=self.suppressed,
                      by_knob=dict(sorted(self.by_knob.items())),
                      quiet=self.actions == 0)

    # -- state the scheduler reads -----------------------------------------

    @property
    def slot_cap(self) -> int:
        return self._slot_cap
