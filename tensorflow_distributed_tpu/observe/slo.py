"""Live SLO monitor for serving: declared targets, rolling windows,
multi-window burn-rate alerting.

An SLO here is a *percentile target* per request class —
``--observe.slo "high:ttft_p95=100ms,tok_p50=30ms"`` declares that 95%
of high-class requests must see first-token latency <= 100 ms and 50%
must see mean inter-token latency <= 30 ms. Each target implies an
**error budget**: ``ttft_p95`` tolerates 5% of requests violating the
threshold; the monitor's job is to say, *while the run is still
serving*, how fast that budget is burning.

Burn rate is the SRE multi-window construction: over a window,
``burn = violating_fraction / budget_fraction`` (1.0 = burning exactly
as fast as the SLO tolerates; 2.0 = the budget gone in half the
period). An alert fires when BOTH a fast and a slow window exceed the
threshold — the fast window gives low detection latency, the slow one
keeps a single straggler from paging — and clears (``slo_ok``) when
either drops back under. Windows are measured on the **decode-step
clock** (the scheduler's own iteration counter), not wall time, so a
test can replay a fixed completion sequence and get the exact same
alert trace every run; the defaults (60 / 600 steps) are the 1m/10m
shape at ~1 step/s.

Pure stdlib (the serve fast test tier imports it jax-free). The
scheduler drives it: :meth:`SLOMonitor.observe` per completion,
:meth:`SLOMonitor.on_step` per decode step; events flow out through
the emit callable (the scheduler's registry) as ``slo_alert`` /
``slo_ok`` records carrying burn rates and error-budget remaining.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Metrics a target may constrain: arrival->first-token latency and
#: mean inter-token latency, both in ms (the two numbers serve_request
#: records already carry).
SLO_METRICS = ("ttft", "tok")


def percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile — THE definition (observe.report
    imports it), so a live snapshot's per-class p95 agrees exactly
    with the post-run report over the same population (slobench gates
    this)."""
    idx = min(len(sorted_vals) - 1,
              max(0, round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One declared objective: ``pct``% of ``cls`` requests must see
    ``metric`` <= ``threshold_ms``. ``cls`` == "" applies to every
    request regardless of class."""

    cls: str
    metric: str            # "ttft" | "tok"
    pct: int               # the percentile, e.g. 95
    threshold_ms: float

    @property
    def budget(self) -> float:
        """Tolerated violating fraction (5% for a p95 target)."""
        return 1.0 - self.pct / 100.0

    @property
    def key(self) -> str:
        base = f"{self.metric}_p{self.pct}"
        return f"{self.cls}:{base}" if self.cls else base


def _parse_value_ms(text: str) -> float:
    text = text.strip()
    for suffix, scale in (("ms", 1.0), ("us", 1e-3), ("s", 1e3)):
        if text.endswith(suffix):
            return float(text[: -len(suffix)]) * scale
    raise ValueError(
        f"SLO value {text!r} needs a unit suffix (ms, s, or us)")


def parse_slo(spec: str) -> List[SLOTarget]:
    """``--observe.slo`` grammar: ``;``-separated class groups, each an
    optional ``class:`` prefix followed by ``,``-separated
    ``metric_pNN=value`` entries —
    ``"high:ttft_p95=100ms,tok_p50=30ms;standard:ttft_p95=500ms"``.
    No prefix = the target applies to every request. Values carry a
    unit suffix (ms/s/us). Duplicate (class, metric, percentile)
    triples are rejected."""
    targets: List[SLOTarget] = []
    seen = set()
    for group in spec.split(";"):
        group = group.strip()
        if not group:
            continue
        cls = ""
        body = group
        if ":" in group:
            head, rest = group.split(":", 1)
            # A bare "ttft_p95=100ms" has no class prefix; a prefix is
            # an identifier with no "=" in it.
            if "=" not in head:
                cls, body = head.strip(), rest
        for entry in body.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" not in entry:
                raise ValueError(
                    f"SLO entry {entry!r} is not metric_pNN=value")
            name, value = (x.strip() for x in entry.split("=", 1))
            if "_p" not in name:
                raise ValueError(
                    f"SLO metric {name!r} is not metric_pNN "
                    f"(e.g. ttft_p95)")
            metric, pct_s = name.rsplit("_p", 1)
            if metric not in SLO_METRICS:
                raise ValueError(
                    f"unknown SLO metric {metric!r}; have {SLO_METRICS}")
            try:
                pct = int(pct_s)
            except ValueError:
                raise ValueError(
                    f"SLO percentile {pct_s!r} in {name!r} is not an "
                    f"integer")
            if not 1 <= pct <= 99:
                raise ValueError(
                    f"SLO percentile must be in [1, 99], got {pct}")
            threshold = _parse_value_ms(value)
            if threshold <= 0:
                raise ValueError(
                    f"SLO threshold for {name!r} must be > 0, got "
                    f"{threshold}ms")
            tgt = SLOTarget(cls=cls, metric=metric, pct=pct,
                            threshold_ms=threshold)
            dup = (cls, metric, pct)
            if dup in seen:
                raise ValueError(
                    f"SLO target {tgt.key!r} declared twice")
            seen.add(dup)
            targets.append(tgt)
    if not targets:
        raise ValueError(f"SLO spec {spec!r} names no targets")
    return targets


def parse_windows(spec: str) -> Tuple[int, int]:
    """``--observe.slo-windows "60,600"`` -> (fast, slow) in decode
    steps, fast < slow, both >= 1."""
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    if len(parts) != 2:
        raise ValueError(
            f"slo_windows {spec!r} must be 'fast,slow' decode-step "
            f"counts")
    fast, slow = int(parts[0]), int(parts[1])
    if not 1 <= fast < slow:
        raise ValueError(
            f"slo_windows must satisfy 1 <= fast < slow, got "
            f"({fast}, {slow})")
    return fast, slow


class _TargetState:
    """Rolling accounting for one target: a slow-window deque of
    (step, value_ms) samples with incrementally-maintained violation
    counts for both windows (on_step runs every decode step — a
    recount per step would be O(window) each)."""

    def __init__(self, target: SLOTarget, fast: int, slow: int):
        self.target = target
        self.fast, self.slow = fast, slow
        self.samples: collections.deque = collections.deque()
        self.fast_n = self.fast_v = 0
        self.slow_n = self.slow_v = 0
        self.total = self.violations = 0
        self.alerting = False
        self.alerts = 0

    def observe(self, value_ms: float, step: int) -> None:
        bad = value_ms > self.target.threshold_ms
        self.samples.append((step, value_ms, bad))
        self.slow_n += 1
        self.fast_n += 1
        self.total += 1
        if bad:
            self.slow_v += 1
            self.fast_v += 1
            self.violations += 1

    def prune(self, step: int) -> None:
        while self.samples and self.samples[0][0] <= step - self.slow:
            _, _, bad = self.samples.popleft()
            self.slow_n -= 1
            self.slow_v -= int(bad)
        # Fast-window counts recount over the (short) fast suffix only
        # when the boundary moved past samples; keep it simple and
        # exact: walk from the right, fast windows are small.
        fn = fv = 0
        for s, _, bad in reversed(self.samples):
            if s <= step - self.fast:
                break
            fn += 1
            fv += int(bad)
        self.fast_n, self.fast_v = fn, fv

    def burn(self) -> Tuple[float, float]:
        budget = self.target.budget
        fast = (self.fast_v / self.fast_n / budget) if self.fast_n else 0.0
        slow = (self.slow_v / self.slow_n / budget) if self.slow_n else 0.0
        return fast, slow

    def budget_remaining(self) -> float:
        """Run-lifetime error budget left: 1 - violations / (budget *
        observed). Negative = overspent."""
        if not self.total:
            return 1.0
        allowed = self.target.budget * self.total
        return round(1.0 - self.violations / max(allowed, 1e-12), 4)

    def window_percentile(self) -> Optional[float]:
        """The target metric's observed percentile over the slow
        window (None without samples) — the status line's number."""
        if not self.samples:
            return None
        vals = sorted(v for _, v, _ in self.samples)
        return percentile(vals, self.target.pct)


class SLOMonitor:
    """Drives burn-rate alerting for a set of targets.

    The scheduler calls :meth:`observe` once per completed request and
    :meth:`on_step` once per decode step (the monitor's clock). Alert
    transitions emit ``slo_alert``/``slo_ok`` through ``emit`` and an
    instant marker through ``tracer`` (both optional). Deterministic
    by construction: same completion sequence on the same step clock
    -> same events.
    """

    def __init__(self, targets: List[SLOTarget], fast_window: int = 60,
                 slow_window: int = 600, burn_threshold: float = 1.0,
                 emit: Optional[Callable[..., Any]] = None,
                 tracer: Any = None, event_prefix: str = ""):
        if not targets:
            raise ValueError("SLOMonitor needs at least one target")
        if burn_threshold <= 0:
            raise ValueError(
                f"burn_threshold must be > 0, got {burn_threshold}")
        fast, slow = int(fast_window), int(slow_window)
        if not 1 <= fast < slow:
            raise ValueError(
                f"windows must satisfy 1 <= fast < slow, got "
                f"({fast}, {slow})")
        self.targets = list(targets)
        self.fast_window, self.slow_window = fast, slow
        self.burn_threshold = burn_threshold
        self._emit = emit
        self._tracer = tracer
        # "fleet_" at the router makes the monitor emit
        # fleet_slo_alert / fleet_slo_ok — same machinery, a namespace
        # that keeps fleet-level and per-replica records separable in
        # one merged JSONL (observe/report.py folds them into
        # different sections).
        self.event_prefix = event_prefix
        self._state = [_TargetState(t, fast, slow) for t in targets]

    def observe(self, slo_class: str, ttft_ms: float, tok_ms: float,
                step: int) -> None:
        """Fold one completion into every matching target's windows."""
        for st in self._state:
            t = st.target
            if t.cls and t.cls != slo_class:
                continue
            value = ttft_ms if t.metric == "ttft" else tok_ms
            st.observe(float(value), int(step))

    def on_step(self, step: int) -> List[Dict[str, Any]]:
        """Advance the decode-step clock: prune windows, evaluate burn
        rates, emit alert transitions. Returns the events emitted this
        step (tests read them directly)."""
        events: List[Dict[str, Any]] = []
        for st in self._state:
            st.prune(step)
            fast, slow = st.burn()
            firing = (fast > self.burn_threshold
                      and slow > self.burn_threshold)
            if firing == st.alerting:
                continue
            st.alerting = firing
            kind = self.event_prefix + (
                "slo_alert" if firing else "slo_ok")
            if firing:
                st.alerts += 1
            fields = {
                "target": st.target.key, "slo_class": st.target.cls,
                "metric": st.target.metric, "pct": st.target.pct,
                "threshold_ms": st.target.threshold_ms,
                "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
                "window_fast": self.fast_window,
                "window_slow": self.slow_window,
                "budget_remaining": st.budget_remaining(),
                "step": int(step),
            }
            events.append({"event": kind, **fields})
            if self._emit is not None:
                self._emit(kind, **fields)
            if self._tracer is not None:
                self._tracer.instant(kind, cat="slo",
                                     target=st.target.key,
                                     burn_fast=fields["burn_fast"],
                                     burn_slow=fields["burn_slow"])
        return events

    # -- read-side --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time SLO state for the scheduler's
        ``metrics_snapshot()`` export: per-target burn rates, observed
        window percentile, budget remaining, alert state."""
        out: Dict[str, Any] = {}
        for st in self._state:
            fast, slow = st.burn()
            entry: Dict[str, Any] = {
                "threshold_ms": st.target.threshold_ms,
                "burn_fast": round(fast, 4),
                "burn_slow": round(slow, 4),
                "budget_remaining": st.budget_remaining(),
                "alerting": st.alerting,
                "alerts": st.alerts,
                "observed": st.total,
            }
            wp = st.window_percentile()
            if wp is not None:
                entry["window_value_ms"] = round(wp, 3)
            out[st.target.key] = entry
        return out

    def summary(self) -> Dict[str, Any]:
        """Run-end rollup merged into the serve_summary record."""
        return {
            "slo_alerts": sum(st.alerts for st in self._state),
            "slo_budget_remaining_min": min(
                st.budget_remaining() for st in self._state),
            "slo_targets": ",".join(t.key for t in self.targets),
        }

    def any_alerting(self) -> bool:
        return any(st.alerting for st in self._state)

    def status_bits(self) -> str:
        """The SLO half of the live status line: per-target observed
        window percentile vs threshold plus the worst burn."""
        bits = []
        for st in self._state:
            _, slow = st.burn()
            wp = st.window_percentile()
            wp_s = "-" if wp is None else f"{wp:.0f}ms"
            mark = "!" if st.alerting else ""
            bits.append(f"{st.target.key}={wp_s}/"
                        f"{st.target.threshold_ms:.0f}ms "
                        f"burn={slow:.2f}{mark}")
        return " ".join(bits)
