"""One-screen fleet status + incident summary.

::

    python -m tensorflow_distributed_tpu.observe.fleetview /tmp/fleet \\
        [--snapshot /tmp/fleet/snapshot.json]

Renders everything a fleet run leaves on disk into one terminal
screen: the control-plane snapshot (``--fleet.export-path``: aggregate
occupancy/queue, per-class end-to-end p95, quarantine set, SLO error
budget, per-replica health), the ``fleet.jsonl`` record stream
(summary, SLO alert transitions, sheds, deaths, latency
decomposition), the stitched ``fleet_trace.json`` (source/balance
stats), and any flight-recorder bundles the replicas left behind
(``flight-*.jsonl`` / ``postmortem-*.jsonl`` under the per-epoch
workspaces). Every section is optional — the view renders whatever
exists and says what is missing, because the most interesting fleets
are the ones that died halfway.

Pure stdlib; :func:`render` returns the screen as a string (tests),
``main`` prints it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

_BAR = "=" * 66


def _load_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail
    except OSError:
        pass
    return out


def _load_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.3f}".rstrip("0").rstrip(".")
    return str(v)


def _snapshot_section(snap: Optional[Dict[str, Any]],
                      lines: List[str]) -> None:
    lines.append("fleet snapshot")
    if snap is None:
        lines.append("  (no snapshot — run with --fleet.export-path)")
        return
    lines.append(
        f"  t={_fmt(snap.get('t_s', 0))}s step={snap.get('step', 0)}  "
        f"requests done={snap.get('requests_done', 0)}"
        f"/{snap.get('requests', 0)} shed={snap.get('requests_shed', 0)}"
        f"  waiting={snap.get('waiting', 0)} "
        f"inflight={snap.get('inflight', 0)}")
    lines.append(
        f"  slots {snap.get('slots_live', 0)}/{snap.get('slots', 0)} "
        f"live, queue={snap.get('queue_depth', 0)}, "
        f"deaths={snap.get('deaths', 0)}, "
        f"quarantined={snap.get('quarantined', []) or 'none'}")
    cls_bits = [
        f"{k[len('ttft_ms_p95_'):]}: p95="
        f"{_fmt(v)}ms/p50={_fmt(snap.get('ttft_ms_p50_' + k[len('ttft_ms_p95_'):], 0))}ms"
        for k, v in sorted(snap.items())
        if k.startswith("ttft_ms_p95_")]
    if cls_bits:
        lines.append("  e2e ttft  " + "  ".join(cls_bits))
    if "slo" in snap:
        mark = " ALERTING" if snap.get("slo_alerting") else ""
        lines.append(
            f"  slo budget remaining min="
            f"{_fmt(snap.get('slo_budget_remaining_min', 1.0))}{mark}")
        for key, ent in sorted(snap["slo"].items()):
            a = "!" if ent.get("alerting") else " "
            lines.append(
                f"   {a}{key}: burn {_fmt(ent.get('burn_fast', 0))}/"
                f"{_fmt(ent.get('burn_slow', 0))} "
                f"budget={_fmt(ent.get('budget_remaining', 1.0))} "
                f"alerts={ent.get('alerts', 0)}")
    reps = snap.get("replicas") or {}
    for name, r in sorted(reps.items()):
        stale = r.get("stale_s")
        lines.append(
            f"  {name:<4} {r.get('health', '?'):<12} "
            f"e{r.get('epoch', 0)} load={r.get('load', 0)} "
            f"inflight={r.get('inflight', 0)} done={r.get('done', 0)}"
            + (f" stale={_fmt(stale)}s" if stale is not None else "")
            + (f" tunes={r['tune_actions']}"
               if "tune_actions" in r else "")
            + (f" [{r['reason']}]" if r.get("reason") else ""))


def _records_section(records: List[Dict[str, Any]],
                     lines: List[str]) -> None:
    lines.append("record stream (fleet.jsonl)")
    if not records:
        lines.append("  (no fleet.jsonl)")
        return
    by_kind: Dict[str, int] = {}
    for r in records:
        by_kind[str(r.get("event"))] = by_kind.get(
            str(r.get("event")), 0) + 1
    summary = next((r for r in reversed(records)
                    if r.get("event") == "fleet_summary"), None)
    if summary is not None:
        lines.append(
            f"  summary: done={summary.get('requests_done', 0)}"
            f"/{summary.get('requests', 0)} "
            f"shed={summary.get('requests_shed', 0)} "
            f"redispatches={summary.get('redispatches', 0)} "
            f"deaths={summary.get('deaths', 0)} "
            f"tok/s={_fmt(summary.get('tokens_per_sec', 0))}")
        cls_bits = [
            f"{k[len('ttft_ms_p95_'):]}={_fmt(v)}ms"
            for k, v in sorted(summary.items())
            if k.startswith("ttft_ms_p95_")]
        if cls_bits:
            lines.append("  e2e ttft p95  " + "  ".join(cls_bits))
    alerts = [r for r in records
              if r.get("event") == "fleet_slo_alert"]
    oks = by_kind.get("fleet_slo_ok", 0)
    lines.append(
        f"  slo: {len(alerts)} alert(s), {oks} all-clear(s)"
        + ("" if not alerts else " — last: " + ", ".join(
            f"{a.get('target')} burn={_fmt(a.get('burn_fast', 0))}"
            for a in alerts[-3:])))
    incidents = [r for r in records if r.get("event") == "fleet_replica"
                 and r.get("state") in ("dead", "quarantined")]
    for r in incidents[-5:]:
        lines.append(
            f"  incident t={_fmt(r.get('t_s', 0))}s "
            f"{r.get('replica')}: {r.get('state')}"
            + (f" ({r['reason']})" if r.get("reason") else ""))
    decomp = [r for r in records if r.get("event") == "fleet_decomp"]
    if decomp:
        n = len(decomp)
        mean = {k: sum(float(d.get(k, 0)) for d in decomp) / n
                for k in ("e2e_ms", "router_queue_ms", "inbox_lag_ms",
                          "replica_queue_ms", "prefill_ms",
                          "decode_ms", "absorb_ms", "residual_ms")}
        lines.append(
            f"  latency decomposition (mean over {n}): "
            f"e2e={mean['e2e_ms']:.1f}ms = "
            f"router_q {mean['router_queue_ms']:.1f} + "
            f"inbox {mean['inbox_lag_ms']:.1f} + "
            f"replica_q {mean['replica_queue_ms']:.1f} + "
            f"prefill {mean['prefill_ms']:.1f} + "
            f"decode {mean['decode_ms']:.1f} + "
            f"absorb {mean['absorb_ms']:.1f} + "
            f"residual {mean['residual_ms']:.1f}")


def _trace_section(fleet_dir: str, lines: List[str]) -> None:
    path = os.path.join(fleet_dir, "fleet_trace.json")
    lines.append("stitched trace")
    data = _load_json(path)
    if data is None:
        lines.append("  (no fleet_trace.json — run with --fleet.trace)")
        return
    events = data.get("traceEvents", [])
    from tensorflow_distributed_tpu.observe.trace import (
        unbalanced_async)
    sources = sorted(
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and str(e.get("args", {}).get("name", "")).startswith("fleet:"))
    bal = not unbalanced_async(events)
    deaths = sum(1 for e in events if e.get("ph") == "e"
                 and (e.get("args") or {}).get("process_death"))
    lines.append(
        f"  {path}: {len(events)} events, "
        f"{len(sources)} sources, "
        f"{'balanced' if bal else 'UNBALANCED'}, "
        f"{deaths} span(s) closed at process death")
    for s in sources:
        lines.append(f"    {s}")


def _flightrec_section(fleet_dir: str, lines: List[str]) -> None:
    bundles = sorted(
        glob.glob(os.path.join(fleet_dir, "*", "e*", "flight-*.jsonl"))
        + glob.glob(os.path.join(fleet_dir, "*", "e*",
                                 "postmortem-*.jsonl")))
    if not bundles:
        return
    lines.append("flight-recorder bundles")
    for b in bundles[-8:]:
        recs = _load_jsonl(b)
        last = recs[-1] if recs else {}
        lines.append(
            f"  {os.path.relpath(b, fleet_dir)}: {len(recs)} records"
            + (f", last={last.get('event')}" if last else ""))


def render(fleet_dir: str, snapshot: str = "") -> str:
    """The one-screen fleet view as a string."""
    snap = None
    for cand in ([snapshot] if snapshot else []) + [
            os.path.join(fleet_dir, "fleet_snapshot.json"),
            os.path.join(fleet_dir, "snapshot.json")]:
        snap = _load_json(cand)
        if snap is not None:
            break
    records = _load_jsonl(os.path.join(fleet_dir, "fleet.jsonl"))
    lines = [_BAR, f"fleet observatory — {fleet_dir}", _BAR]
    _snapshot_section(snap, lines)
    lines.append("")
    _records_section(records, lines)
    lines.append("")
    _trace_section(fleet_dir, lines)
    _flightrec_section(fleet_dir, lines)
    lines.append(_BAR)
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tensorflow_distributed_tpu.observe.fleetview",
        description="one-screen fleet status + incident summary")
    parser.add_argument("fleet_dir",
                        help="the fleet run's --fleet-dir")
    parser.add_argument("--snapshot", default="",
                        help="the --fleet.export-path file (default: "
                        "fleet_snapshot.json under the fleet dir)")
    opts = parser.parse_args(argv)
    if not os.path.isdir(opts.fleet_dir):
        print(f"fleetview: {opts.fleet_dir} is not a directory",
              file=sys.stderr)
        return 2
    print(render(opts.fleet_dir, opts.snapshot))
    return 0


if __name__ == "__main__":
    sys.exit(main())
