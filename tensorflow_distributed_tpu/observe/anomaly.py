"""Online anomaly detection over the observe record flow.

Everything else in observe/ tells you what happened; this module says
*something is going wrong, now*. A set of streaming detectors consumes
the values the run ALREADY fetches on its log cadence — no new host
transfers, no device work — and emits ``event="anomaly"`` records
(detector id, severity, the offending value, the rolling baseline, an
evidence window) the moment a signal leaves its envelope:

- **step-time spike** (:class:`MadSpikeDetector`): robust z-score
  against the rolling median/MAD — a stall, a swap-in, a noisy
  neighbor shows as one step far outside the jitter envelope;
- **throughput-slope degradation** (:class:`SlopeDegradationDetector`):
  the newer half of the window sustainedly below the older half — the
  slow-leak failure a single-step spike detector cannot see;
- **loss spike** (:class:`RollingMedianSpike`) — THE implementation
  behind ``resilience.policies.LossSpikeDetector`` (one rolling-median
  spike rule in the repo, not two) — and **loss plateau**
  (:class:`PlateauDetector`) / **non-finite loss**
  (:class:`NonFiniteDetector`);
- **grad-norm explosion / update-ratio collapse** on the per-module
  health records (observe/health.py): a layer diverging or freezing
  flags before the global loss moves;
- serve side, on the **deterministic decode-step clock**: TTFT spike,
  decode-step-time spike, sustained queue growth
  (:class:`QueueGrowthDetector`), and per-slot non-finite logits (the
  engine's own ok-flag, surfaced as an anomaly).

The :class:`AnomalyHub` owns one run's detectors, routes the observed
values (the Observatory feeds it from ``log_step``/health records, the
serve scheduler from its decode loop), emits through the run's
registry, and keeps the live incident state
(:meth:`AnomalyHub.snapshot`) that ``Scheduler.metrics_snapshot()``
and the ``--observe.export-path`` payload carry for a router or fleet
supervisor to poll.

Detection quality is gateable, not aspirational: the resilience fault
plans are deterministic ground truth, and ``benchmarks/detectbench.py``
(committed ``DETECTBENCH.json``) gates recall (every injected fault
kind flagged within K steps), precision (a seeded clean run stays
silent), and instrumentation overhead.

Pure stdlib — the fast test tier imports it jax-free.
"""

from __future__ import annotations

import collections
import math
import statistics
from typing import Any, Callable, Dict, List, Optional

#: Severity levels, mild first. "warn" = degradation worth a look;
#: "critical" = the run is actively damaged (non-finite values,
#: explosions).
SEVERITIES = ("warn", "critical")


def _finite(value: Any) -> bool:
    return isinstance(value, (int, float)) and math.isfinite(value)


class MadSpikeDetector:
    """Robust single-sample spike detection: rolling median + MAD.

    A sample fires when BOTH hold over a window of at least
    ``min_samples`` prior values:

    - robust z-score ``(value - median) / max(MAD/0.6745,
      0.01*|median|)`` exceeds ``z_threshold`` (the 1%-of-median floor
      keeps a near-constant baseline — MAD ~ 0 — from turning timer
      quantization into infinite z);
    - ``value > ratio_min * median`` AND ``value - median > abs_min``
      (scale guards: relative jitter on a tiny baseline — sub-ms
      decode steps easily double on host scheduling noise — never
      fires; an incident must be large in BOTH senses).

    A firing sample is NOT added to the window (one outlier must not
    drag the baseline toward itself) and starts a ``cooldown`` during
    which further samples are absorbed into the window without firing
    — a sustained regime shift re-baselines instead of paging every
    step.
    """

    def __init__(self, id: str, window: int = 64, min_samples: int = 8,
                 z_threshold: float = 8.0, ratio_min: float = 4.0,
                 abs_min: float = 0.0,
                 severity: str = "warn", evidence: int = 8):
        self.id = id
        self.severity = severity
        self.min_samples = max(2, int(min_samples))
        self.z_threshold = float(z_threshold)
        self.ratio_min = float(ratio_min)
        self.abs_min = float(abs_min)
        self.evidence = int(evidence)
        self._buf: collections.deque = collections.deque(
            maxlen=int(window))
        self._cool = 0
        self._cooldown = self.min_samples

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        if not _finite(value):
            return None
        value = float(value)
        if self._cool > 0:
            self._cool -= 1
            self._buf.append(value)
            return None
        if len(self._buf) >= self.min_samples:
            med = statistics.median(self._buf)
            mad = statistics.median(abs(x - med) for x in self._buf)
            denom = max(mad / 0.6745, 0.01 * abs(med), 1e-9)
            z = (value - med) / denom
            if (z > self.z_threshold and med > 0
                    and value > self.ratio_min * med
                    and value - med > self.abs_min):
                self._cool = self._cooldown
                return {
                    "value": value, "baseline": med,
                    "zscore": min(z, 1e6),
                    "evidence": list(self._buf)[-self.evidence:],
                }
        self._buf.append(value)
        return None

    def reset(self) -> None:
        self._buf.clear()
        self._cool = 0


class RollingMedianSpike:
    """Rolling-window divergence detector for FINITE values — the ONE
    median-spike implementation (``resilience.policies
    .LossSpikeDetector`` is this class, so the loop's acting policy
    and the anomaly hub's advisory detector cannot drift apart).

    ``observe(value)`` returns the window median when ``value >
    factor * median`` over a full window, else None. The spiking value
    is NOT added to the window (one outlier must not drag the baseline
    toward itself), but training-regime shifts still track because
    every non-spike value is."""

    def __init__(self, window: int, factor: float):
        self.factor = factor
        self._window: collections.deque = collections.deque(
            maxlen=window)

    def observe(self, loss: float) -> Optional[float]:
        full = len(self._window) == self._window.maxlen
        if full:
            med = statistics.median(self._window)
            if loss > self.factor * max(med, 1e-12):
                return med
        self._window.append(loss)
        return None

    def reset(self) -> None:
        """After a rewind the replayed steps re-approach the spike
        region legitimately; a stale window would re-flag them."""
        self._window.clear()


class SlopeDegradationDetector:
    """Sustained degradation of a higher-is-better signal
    (throughput): over a FULL window, the newer half's median below
    ``(1 - drop) x`` the older half's median. One dipped sample never
    fires — half the window must sit down there. On fire the window
    clears (the new regime becomes the baseline; re-arms after a full
    window of fresh samples)."""

    def __init__(self, id: str, window: int = 12, drop: float = 0.4,
                 severity: str = "warn"):
        if window < 4:
            raise ValueError(f"window must be >= 4, got {window}")
        self.id = id
        self.severity = severity
        self.drop = float(drop)
        self._buf: collections.deque = collections.deque(
            maxlen=int(window))

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        if not _finite(value):
            return None
        self._buf.append(float(value))
        if len(self._buf) < self._buf.maxlen:
            return None
        vals = list(self._buf)
        half = len(vals) // 2
        old = statistics.median(vals[:half])
        new = statistics.median(vals[half:])
        if old > 0 and new < (1.0 - self.drop) * old:
            self._buf.clear()
            return {"value": new, "baseline": old,
                    "evidence": vals[-8:]}
        return None

    def reset(self) -> None:
        self._buf.clear()


class PlateauDetector:
    """A lower-is-better signal (loss) that stopped improving: over a
    FULL window, the relative improvement of the newer half's median
    vs the older half's is below ``min_improve`` in magnitude (a
    worsening signal is the spike detectors' territory — it does not
    read as a plateau). Long default window: a plateau is a
    macro-scale judgment, not a per-step one."""

    def __init__(self, id: str, window: int = 256,
                 min_improve: float = 0.005, severity: str = "warn"):
        if window < 8:
            raise ValueError(f"window must be >= 8, got {window}")
        self.id = id
        self.severity = severity
        self.min_improve = float(min_improve)
        self._buf: collections.deque = collections.deque(
            maxlen=int(window))

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        if not _finite(value):
            return None
        self._buf.append(float(value))
        if len(self._buf) < self._buf.maxlen:
            return None
        vals = list(self._buf)
        half = len(vals) // 2
        old = statistics.median(vals[:half])
        new = statistics.median(vals[half:])
        improve = (old - new) / max(abs(old), 1e-12)
        if abs(improve) < self.min_improve:
            self._buf.clear()
            return {"value": new, "baseline": old,
                    "improvement": improve}
        return None

    def reset(self) -> None:
        self._buf.clear()


class NonFiniteDetector:
    """A value that should always be finite went NaN/inf — fires
    immediately (no window), critical by default."""

    def __init__(self, id: str, severity: str = "critical"):
        self.id = id
        self.severity = severity

    def observe(self, value: Any) -> Optional[Dict[str, Any]]:
        if isinstance(value, (int, float)) and not math.isfinite(value):
            return {"value": str(value)}
        return None

    def reset(self) -> None:
        pass


class RatioCollapseDetector:
    """A should-stay-positive signal (per-module update ratio)
    collapsing toward zero: over a full window, ``value < median /
    factor``. The frozen-layer signature — the explosion direction is
    :class:`MadSpikeDetector`'s job. Collapsing samples are not added
    (the baseline must keep describing healthy steps); a cooldown
    absorbs a sustained collapse into one event per window."""

    def __init__(self, id: str, window: int = 32, factor: float = 50.0,
                 floor: float = 1e-12, severity: str = "warn"):
        self.id = id
        self.severity = severity
        self.factor = float(factor)
        self.floor = float(floor)
        self._buf: collections.deque = collections.deque(
            maxlen=int(window))
        self._cool = 0

    def observe(self, value: float) -> Optional[Dict[str, Any]]:
        if not _finite(value):
            return None
        value = float(value)
        if self._cool > 0:
            self._cool -= 1
            self._buf.append(value)
            return None
        if len(self._buf) == self._buf.maxlen:
            med = statistics.median(self._buf)
            if med > self.floor and value < med / self.factor:
                self._cool = self._buf.maxlen
                return {"value": value, "baseline": med,
                        "evidence": list(self._buf)[-8:]}
        self._buf.append(value)
        return None

    def reset(self) -> None:
        self._buf.clear()
        self._cool = 0


class QueueGrowthDetector:
    """Sustained backlog growth on a deterministic clock: over a FULL
    window of queue-depth samples, net growth of at least
    ``min_growth`` with the backlog AT its window maximum (still
    growing, not draining). Fires once per window (the buffer clears),
    so a standing backlog pages once per window, not per step."""

    def __init__(self, id: str, window: int = 32, min_growth: int = 8,
                 severity: str = "warn"):
        if window < 4:
            raise ValueError(f"window must be >= 4, got {window}")
        self.id = id
        self.severity = severity
        self.min_growth = int(min_growth)
        self._buf: collections.deque = collections.deque(
            maxlen=int(window))

    def observe(self, depth: float) -> Optional[Dict[str, Any]]:
        if not _finite(depth):
            return None
        depth = float(depth)
        self._buf.append(depth)
        if len(self._buf) < self._buf.maxlen:
            return None
        vals = list(self._buf)
        if (vals[-1] - vals[0] >= self.min_growth
                and vals[-1] >= max(vals)):
            self._buf.clear()
            return {"value": vals[-1], "baseline": vals[0],
                    "evidence": vals[-8:]}
        return None

    def reset(self) -> None:
        self._buf.clear()


class AnomalyHub:
    """One run's incident brain: owns the detector set for its phase
    (``train`` or ``serve``), routes observed values, emits
    ``anomaly`` records through ``emit`` (the run's registry), and
    tracks the live state :meth:`snapshot` exports.

    Every ``observe_*`` method returns the list of anomaly records it
    fired (tests read them directly; callers may ignore the return).
    All feeds consume values the caller already has on host — the hub
    itself never touches a device.
    """

    def __init__(self, emit: Optional[Callable[..., Any]] = None,
                 window: int = 64, phase: str = "train"):
        if phase not in ("train", "serve"):
            raise ValueError(
                f"unknown anomaly phase {phase!r}; have "
                f"('train', 'serve')")
        if window < 8:
            raise ValueError(f"anomaly window must be >= 8, "
                             f"got {window}")
        self.emit = emit
        self.phase = phase
        self.window = int(window)
        self.count = 0
        self.by_detector: Dict[str, int] = {}
        self.last: Optional[Dict[str, Any]] = None
        self._cur_step = 0
        self._fired_step: Dict[str, int] = {}
        if phase == "train":
            self._loss_nonfinite = NonFiniteDetector("loss_nonfinite")
            self._loss_spike = RollingMedianSpike(
                window=max(4, window // 8), factor=4.0)
            self._loss_plateau = PlateauDetector(
                "loss_plateau", window=4 * window)
            # Time-scale detectors carry a 50 ms absolute-excess
            # floor: relative jitter on a small baseline (host
            # scheduling noise on ms-scale steps) is not an incident.
            self._step_time = MadSpikeDetector(
                "step_time_spike", window=window, abs_min=50.0)
            self._throughput = SlopeDegradationDetector(
                "throughput_slope", window=max(8, window // 4))
            self._grad_norm = MadSpikeDetector(
                "grad_norm_spike", window=window,
                severity="critical")
        else:
            self._ttft = MadSpikeDetector("ttft_spike", window=window,
                                          abs_min=50.0)
            self._decode_time = MadSpikeDetector(
                "decode_time_spike", window=window, abs_min=50.0)
            self._queue = QueueGrowthDetector(
                "queue_growth", window=max(8, window // 2))
        # Per-module health detectors, created lazily as modules
        # appear in the health records.
        self._health: Dict[str, Any] = {}

    # -- emission ---------------------------------------------------------

    def _fire(self, detector: str, severity: str, step: int,
              finding: Dict[str, Any], **extra: Any) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"detector": detector,
                               "severity": severity,
                               "step": int(step)}
        for key, val in finding.items():
            if isinstance(val, float):
                rec[key] = round(val, 6)
            elif isinstance(val, list):
                rec[key] = [round(v, 6) if isinstance(v, float) else v
                            for v in val]
            else:
                rec[key] = val
        rec.update(extra)
        self.count += 1
        self.by_detector[detector] = (
            self.by_detector.get(detector, 0) + 1)
        self.last = rec
        self._fired_step[detector] = int(step)
        if self.emit is not None:
            self.emit("anomaly", **rec)
        return rec

    def _note_step(self, step: int) -> None:
        self._cur_step = max(self._cur_step, int(step))

    # -- train feeds (Observatory.log_step / health records) --------------

    def observe_train_step(self, step: int, metrics: Dict[str, Any],
                           step_wall_ms: Optional[float] = None
                           ) -> List[Dict[str, Any]]:
        """One log-cadence sample: the fetched task metrics plus the
        cadence-derived per-step wall (None on the first log, which
        has no previous cadence to difference against)."""
        self._note_step(step)
        fired: List[Dict[str, Any]] = []
        loss = metrics.get("loss")
        if isinstance(loss, (int, float)):
            f = self._loss_nonfinite.observe(loss)
            if f is not None:
                fired.append(self._fire(
                    "loss_nonfinite", self._loss_nonfinite.severity,
                    step, f))
            else:
                med = self._loss_spike.observe(float(loss))
                if med is not None:
                    fired.append(self._fire(
                        "loss_spike", "warn", step,
                        {"value": float(loss), "baseline": med,
                         "factor": self._loss_spike.factor}))
                f = self._loss_plateau.observe(float(loss))
                if f is not None:
                    fired.append(self._fire(
                        "loss_plateau", self._loss_plateau.severity,
                        step, f))
        if step_wall_ms is not None:
            f = self._step_time.observe(step_wall_ms)
            if f is not None:
                fired.append(self._fire(
                    "step_time_spike", self._step_time.severity,
                    step, f))
        for key in ("tokens_per_sec", "images_per_sec",
                    "items_per_sec"):
            if isinstance(metrics.get(key), (int, float)):
                f = self._throughput.observe(float(metrics[key]))
                if f is not None:
                    fired.append(self._fire(
                        "throughput_slope", self._throughput.severity,
                        step, f, signal=key))
                break
        if isinstance(metrics.get("grad_norm"), (int, float)):
            f = self._grad_norm.observe(float(metrics["grad_norm"]))
            if f is not None:
                fired.append(self._fire(
                    "grad_norm_spike", self._grad_norm.severity,
                    step, f))
        return fired

    def observe_health(self, step: int, module: str,
                       fields: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One per-module health record (observe/health.py): grad-norm
        explosion and update-ratio collapse, per module."""
        self._note_step(step)
        fired: List[Dict[str, Any]] = []
        dets = self._health.get(module)
        if dets is None:
            dets = self._health[module] = {
                "grad": MadSpikeDetector(
                    f"grad_norm_spike/{module}", window=self.window,
                    severity="critical"),
                "ratio": RatioCollapseDetector(
                    f"update_ratio_collapse/{module}",
                    window=max(8, self.window // 2)),
            }
        if isinstance(fields.get("grad_norm"), (int, float)):
            f = dets["grad"].observe(float(fields["grad_norm"]))
            if f is not None:
                fired.append(self._fire(
                    dets["grad"].id, dets["grad"].severity, step, f,
                    module=module))
        if isinstance(fields.get("update_ratio"), (int, float)):
            f = dets["ratio"].observe(float(fields["update_ratio"]))
            if f is not None:
                fired.append(self._fire(
                    dets["ratio"].id, dets["ratio"].severity, step, f,
                    module=module))
        return fired

    # -- serve feeds (scheduler, on the decode-step clock) ----------------

    def observe_decode_step(self, step: int,
                            queue_depth: Optional[int] = None,
                            step_wall_ms: Optional[float] = None
                            ) -> List[Dict[str, Any]]:
        """One decode step: the dispatch wall (decode-stall detection)
        and the queue depth (sustained-backlog detection)."""
        self._note_step(step)
        fired: List[Dict[str, Any]] = []
        if step_wall_ms is not None:
            f = self._decode_time.observe(step_wall_ms)
            if f is not None:
                fired.append(self._fire(
                    "decode_time_spike", self._decode_time.severity,
                    step, f))
        if queue_depth is not None:
            f = self._queue.observe(queue_depth)
            if f is not None:
                fired.append(self._fire(
                    "queue_growth", self._queue.severity, step, f))
        return fired

    def observe_completion(self, step: int, ttft_ms: float
                           ) -> List[Dict[str, Any]]:
        """One completed request's TTFT, on the decode-step clock."""
        self._note_step(step)
        f = self._ttft.observe(ttft_ms)
        if f is not None:
            return [self._fire("ttft_spike", self._ttft.severity,
                               step, f)]
        return []

    def note_slot_nonfinite(self, step: int, slot: Optional[int] = None,
                            rid: Optional[int] = None
                            ) -> List[Dict[str, Any]]:
        """The engine's per-slot finiteness flag tripped (the value is
        already on host — the scheduler quarantines on it); surface it
        as a critical anomaly immediately."""
        self._note_step(step)
        extra: Dict[str, Any] = {}
        if slot is not None:
            extra["slot"] = int(slot)
        if rid is not None:
            extra["rid"] = int(rid)
        return [self._fire("slot_nonfinite", "critical", step, {},
                           **extra)]

    # -- read side --------------------------------------------------------

    def active(self) -> List[str]:
        """Detectors that fired within the last ``window`` steps of
        the hub's clock — the "is something wrong RIGHT NOW" set."""
        return sorted(
            det for det, at in self._fired_step.items()
            if self._cur_step - at <= self.window)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able incident state for ``metrics_snapshot()`` / the
        ``--observe.export-path`` payload: total count, per-detector
        counts, currently-active detectors, and the last anomaly."""
        out: Dict[str, Any] = {
            "anomalies": self.count,
            "active": self.active(),
            "by_detector": dict(sorted(self.by_detector.items())),
        }
        if self.last is not None:
            out["last"] = {k: self.last[k] for k in
                           ("detector", "severity", "step")
                           if k in self.last}
            if "value" in self.last:
                out["last"]["value"] = self.last["value"]
        return out
