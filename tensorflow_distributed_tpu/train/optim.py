"""Optimizer construction.

The reference used ``tf.train.AdamOptimizer`` with its slots (m, v)
living on the ps like every other variable (mnist_python_m.py:208,
SURVEY.md N12). Here the optimizer is an optax transformation whose
state is sharded exactly like the params (on-chip, replicated or
partitioned) — there is no ps for it to live on.
"""

from __future__ import annotations

import optax

from tensorflow_distributed_tpu.config import TrainConfig


def make_schedule(cfg: TrainConfig) -> optax.Schedule:
    if cfg.lr_schedule == "constant":
        return optax.constant_schedule(cfg.learning_rate)
    if cfg.lr_schedule == "cosine":
        return optax.cosine_decay_schedule(cfg.learning_rate, cfg.train_steps)
    if cfg.lr_schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=cfg.learning_rate,
            warmup_steps=max(cfg.warmup_steps, 1),
            decay_steps=max(cfg.train_steps, cfg.warmup_steps + 1))
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r}")


# Leaf NAMES that receive weight decay: projection kernels, embedding
# tables, and the MoE expert/router matrices. Name-based, NOT
# shape-based (ndim >= 2), deliberately: DenseGeneral biases are rank
# (3, H, Dh), and the pipelined family stacks EVERY leaf (biases and
# norm scales included) to rank N+2 — a shape rule would decay them.
_DECAY_LEAF_NAMES = ("kernel", "embedding", "wi", "wo", "gate")


def decay_mask(params):
    """Standard weight-decay mask: decay weight MATRICES only (by leaf
    name — _DECAY_LEAF_NAMES), never biases or norm scales/offsets.
    Decaying norm scales pulls them toward zero, which fights the
    normalization itself — the GPT-2/BERT recipes exclude them, and so
    does every optimizer here."""
    import jax

    def walk(path, leaf):
        name = path[-1].key if path else ""
        return name in _DECAY_LEAF_NAMES

    return jax.tree_util.tree_map_with_path(walk, params)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    sched = make_schedule(cfg)
    if cfg.optimizer == "adam":
        if cfg.weight_decay:
            core = optax.adamw(sched, weight_decay=cfg.weight_decay,
                               mask=decay_mask)
        else:
            core = optax.adam(sched)
    elif cfg.optimizer == "sgd":
        core = optax.sgd(sched, momentum=0.9)
    elif cfg.optimizer == "adafactor":
        # Factored second moments: O(rows + cols) optimizer state per
        # matrix instead of Adam's O(rows * cols) — the classic
        # TPU-scale choice, and multiplicative with FSDP's 1/data
        # sharding of whatever state remains.
        core = optax.adafactor(
            sched,
            weight_decay_rate=cfg.weight_decay or None,
            weight_decay_mask=decay_mask if cfg.weight_decay else None)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.grad_clip_norm and cfg.grad_sync == "implicit":
        # The chain clip sees the FULL replicated grad tree only on the
        # implicit path. The explicit shard_map step (grad_sync=serial/
        # overlap) hands tx SHARDED grad blocks — a chain clip there
        # would clip by each device's local block norm — so the step
        # applies the clip itself from a psum-reconstructed global norm
        # BEFORE tx.update (parallel/overlap.py, grad_clip_norm arg)
        # and the chain stays clip-free.
        return optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), core)
    return core
