"""Multi-step runner: K train steps per host dispatch.

The reference paid a full host->runtime round-trip per step (feed_dict
+ sess.run, SURVEY.md N14) and so did our plain loop — one dispatch,
one batch transfer, one step. On TPU the idiomatic fix is to move the
loop onto the device: stack K batches, ship them in one transfer, and
``lax.scan`` the train step K times inside one jitted program. Host
work (and tunnel/PCIe latency) amortizes K-fold; XLA overlaps the next
scan iteration's data slice with compute.

Composes with the ``preprocess`` hook so the transfer can carry raw
uint8 pixels (4x fewer bytes than f32) and normalization runs on
device — move bytes, not floats.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.train.state import TrainState
from tensorflow_distributed_tpu.train.step import (
    LossFn, Metrics, default_batch_shardings, loss_fn, make_train_step)


def stacked_batch_shardings(mesh: Mesh, batch_shardings: Any = None) -> Any:
    """Shift each batch sharding right one dim for the leading K dim."""
    if batch_shardings is None:
        batch_shardings = default_batch_shardings(mesh)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, P(None, *s.spec)), batch_shardings)


def make_multi_step(mesh: Mesh, seed: int = 0, loss: LossFn = loss_fn,
                    batch_shardings: Any = None,
                    preprocess: Optional[Callable[[Any], Any]] = None,
                    accum_steps: int = 1,
                    health_every: int = 0,
                    grad_sync: str = "implicit",
                    state_template: Any = None,
                    grad_sync_bucket_bytes: int = 0,
                    grad_sync_min_size: int = 0,
                    grad_clip_norm: float = 0.0
                    ) -> Callable[[TrainState, Any],
                                  Tuple[TrainState, Metrics]]:
    """Build ``fn(state, stacked_batches) -> (state, metrics_of_last)``.

    ``stacked_batches`` leaves carry a leading K dim (any K; one compile
    per K). ``preprocess`` runs on-device on each scanned slice before
    the step (e.g. u8 -> f32 normalize). ``health_every`` threads the
    per-module health cadence into the inner step (train.step); the
    returned metrics being the LAST scanned step's, a cadence that
    divides K reports the vitals of that dispatch's final step.
    ``grad_sync`` != "implicit" scans the EXPLICIT collective step
    (parallel.overlap; needs ``state_template`` like train.step's
    dispatch) — the bucketed reduce-scatter/all-gather schedule runs
    inside every scan iteration, so K on-device steps keep the same
    overlap window a dispatched-per-step loop gets.
    """
    base = make_train_step(mesh, seed=seed, loss=loss,
                           batch_shardings=batch_shardings,
                           accum_steps=accum_steps, jit=False,
                           health_every=health_every,
                           grad_sync=grad_sync,
                           state_template=state_template,
                           grad_sync_bucket_bytes=grad_sync_bucket_bytes,
                           grad_sync_min_size=grad_sync_min_size,
                           grad_clip_norm=grad_clip_norm)

    def run(state: TrainState, batches: Any) -> Tuple[TrainState, Metrics]:
        def body(s, b):
            if preprocess is not None:
                b = preprocess(b)
            return base(s, b)

        state, metrics = jax.lax.scan(body, state, batches)
        # Last step's metrics: enough for cadence logging, and keeps the
        # output transfer O(1) in K.
        return state, jax.tree_util.tree_map(lambda m: m[-1], metrics)

    with mesh:
        return observe_device.instrument_jit(
            "multi_step", run,
            in_shardings=(None, stacked_batch_shardings(mesh,
                                                        batch_shardings)),
            donate_argnums=(0,),
        )
