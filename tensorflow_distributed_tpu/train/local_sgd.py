"""Local SGD: the TPU-native runnable analog of the reference's async mode.

The reference's ``sync_replicas=False`` path (mnist_python_m.py:208,222,
247-253, SURVEY.md N6) lets each worker push updates to the ps without
waiting — workers train on stale, mutually-diverged parameters between
ps round-trips. A TPU mesh has no parameter server and SPMD programs
are synchronous by construction, so a literal port is impossible AND
undesirable (the measured 19.9x allreduce-vs-ps gap, GRADSYNC_r03).
What survives contact with the hardware is the async family's actual
training-dynamics content: REPLICAS THAT DIVERGE BETWEEN SYNC POINTS.

That is local SGD / periodic parameter averaging (a.k.a. post-local
SGD): every data-parallel replica takes ``sync_every`` optimizer steps
on its own batch shard with NO gradient sync, then replicas average
their parameters — one pmean every H steps instead of one psum every
step, an H-fold cut in sync frequency, which is precisely the
communication behavior async-ps buys (at the cost of divergence, which
is also exactly async-ps's cost). At H=1 with plain SGD it IS
synchronous data parallelism: avg(p - lr*g_r) == p - lr*avg(g_r) —
pinned as an exact parity test.

Mechanics: the train state's params/opt-state/step carry a leading
replica dim [R, ...] sharded over the "data" mesh axis; the step runs
in a shard_map manualizing only that axis, so each device updates its
own replica locally (per-replica dropout keys included), and a
``lax.cond``-gated ``pmean`` averages params every H-th step. Plain-DP
meshes only (model/seq/pipe/expert == 1) — the same scope the
reference's async mode had.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA
from tensorflow_distributed_tpu.train.state import TrainState
from tensorflow_distributed_tpu.train.step import (
    default_batch_shardings, loss_fn)
from tensorflow_distributed_tpu.utils import prng


def stack_state(state: TrainState, mesh: Mesh) -> TrainState:
    """Broadcast params/opt_state/step to [R, ...] replica-stacked
    leaves sharded over the data axis. The replicas start identical
    (the reference's workers also all began from the chief's init,
    mnist_python_m.py:272-275) and diverge from the first local step."""
    if state.extra:
        raise ValueError(
            "local SGD supports models without mutable extra state "
            f"(got collections {list(state.extra)}); divergent per-"
            "replica statistics have no principled average")
    if state.ema is not None:
        raise ValueError("local SGD does not compose with ema_decay "
                         "(average-of-averages ambiguity); disable one")
    R = mesh.shape[AXIS_DATA]

    # Jitted with sharded out_shardings: XLA writes only each
    # device's 1/R shard of the broadcast — no transient R-fold
    # replicated copy of params + optimizer slots (an OOM risk at
    # exactly the scale local SGD targets).
    def bcast_tree(tree):
        return jax.jit(
            lambda t: jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (R,) + x.shape),
                t),
            out_shardings=NamedSharding(mesh, P(AXIS_DATA)))(tree)

    return state.replace(
        step=bcast_tree(jnp.asarray(state.step)),
        params=bcast_tree(state.params),
        opt_state=bcast_tree(jax.tree_util.tree_map(
            jnp.asarray, state.opt_state)))


def averaged_view(state: TrainState) -> TrainState:
    """The cross-replica mean view for eval/reporting: PARAMS average
    over the replica dim (int leaves take replica 0); the opt state
    takes replica 0 unaveraged — no consumer reads it (eval uses
    params only) and element-wise-averaged Adam moments would not be
    a principled warm start anyway."""
    def mean0(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.mean(x, axis=0)
        return x[0]

    return state.replace(
        step=state.step[0],
        params=jax.tree_util.tree_map(mean0, state.params),
        opt_state=jax.tree_util.tree_map(lambda o: jnp.asarray(o)[0],
                                         state.opt_state))


def make_local_sgd_train_step(mesh: Mesh, sync_every: int, seed: int = 0,
                              loss: Any = loss_fn,
                              batch_shardings: Any = None,
                              donate: bool = True,
                              grad_norm_metric: bool = False
                              ) -> Callable[[TrainState, Any],
                                            Tuple[TrainState, Dict]]:
    """Build the jitted local-SGD step (see module docstring).

    Consumes/produces the replica-stacked TrainState from
    ``stack_state``. Metrics are replica means every step. Parameters
    are averaged when ``(step + 1) % sync_every == 0``, so step counts
    H-1 local steps then a sync step, repeating."""
    if sync_every < 1:
        raise ValueError(f"sync_every must be >= 1, got {sync_every}")
    if batch_shardings is None:
        batch_shardings = default_batch_shardings(mesh)
    batch_specs = jax.tree_util.tree_map(
        lambda s: s.spec, batch_shardings,
        is_leaf=lambda s: isinstance(s, NamedSharding))

    def step(state: TrainState, batch: Any) -> Tuple[TrainState, Dict]:
        apply_fn, tx = state.apply_fn, state.tx

        def per_replica(params_s, opt_s, step_s, local_batch):
            params = jax.tree_util.tree_map(lambda p: p[0], params_s)
            opt = jax.tree_util.tree_map(lambda o: o[0], opt_s)
            stp = step_s[0]
            r = jax.lax.axis_index(AXIS_DATA)
            # Distinct dropout per replica per step — replicas must
            # diverge by data AND noise, like the reference's workers.
            dkey = jax.random.fold_in(prng.step_key(seed, stp), r)
            grad_fn = jax.value_and_grad(partial(loss, apply_fn),
                                         has_aux=True)
            (_, (metrics, _)), grads = grad_fn(params, {}, local_batch,
                                               dkey, True)
            if grad_norm_metric:
                import optax
                metrics = dict(metrics,
                               grad_norm=optax.global_norm(grads))
            updates, new_opt = tx.update(grads, opt, params)
            new_params = jax.tree_util.tree_map(
                lambda p, u: p + u.astype(p.dtype), params, updates)
            do_sync = (stp + 1) % sync_every == 0
            new_params = jax.lax.cond(
                do_sync,
                lambda p: jax.tree_util.tree_map(
                    lambda t: jax.lax.pmean(t, AXIS_DATA), p),
                lambda p: p, new_params)
            metrics = jax.tree_util.tree_map(
                lambda m: jax.lax.pmean(m, AXIS_DATA), metrics)
            restack = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda x: x[None], t)
            return (restack(new_params), restack(new_opt),
                    (stp + 1)[None], metrics)

        new_params, new_opt, new_step, metrics = jax.shard_map(
            per_replica, mesh=mesh, axis_names={AXIS_DATA},
            in_specs=(P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA),
                      batch_specs),
            out_specs=(P(AXIS_DATA), P(AXIS_DATA), P(AXIS_DATA), P()),
            check_vma=False)(state.params, state.opt_state, state.step,
                             batch)
        return state.replace(step=new_step, params=new_params,
                             opt_state=new_opt), metrics

    with mesh:
        from tensorflow_distributed_tpu.observe import (
            device as observe_device)
        return observe_device.instrument_jit(
            "local_sgd_step", step,
            in_shardings=(None, batch_shardings),
            donate_argnums=(0,) if donate else ())
