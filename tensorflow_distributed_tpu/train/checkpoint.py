"""Step-tagged checkpoint save/restore.

Replaces the reference's ``tf.train.Supervisor`` checkpointing
(mnist_python_m.py:236-253, SURVEY.md N7) minus its defining bug: the
reference checkpointed to a fresh ``tempfile.mkdtemp()`` (:236), making
cross-run resume impossible by construction (SURVEY.md Appendix B.3).
Here checkpoints go to a durable directory, tagged by step, with
explicit resume.

Design:
- One directory per checkpoint: ``<dir>/step_00001234/`` containing the
  full train-state pytree (params + optimizer state + step) as msgpack
  plus a small JSON manifest. Writes are atomic (tmp dir + rename), so
  a crash mid-save never corrupts the latest checkpoint — the recovery
  story the Supervisor's background saver provided (:245,:252).
- Only the chief process writes (parallel.mesh.is_chief); every process
  restores. Params are fetched to host via ``jax.device_get`` — for the
  model sizes this framework targets per-host full gathers are fine;
  sharded per-host saves are an orbax upgrade path documented here.
- Restore places leaves back on the mesh with the *current* state's
  shardings, so a checkpoint saved on one mesh shape restores onto
  another (e.g. train on 8 chips, fine-tune on 1).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, List, Optional

import jax
import numpy as np
from flax import serialization

from tensorflow_distributed_tpu.parallel.mesh import is_chief

_STEP_PREFIX = "step_"


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"{_STEP_PREFIX}{step:08d}")


def available_steps(ckpt_dir: str) -> List[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith(_STEP_PREFIX):
            try:
                out.append(int(name[len(_STEP_PREFIX):]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def save(ckpt_dir: str, state: Any, keep: int = 3) -> str:
    """Write state at its current step; prune to the newest ``keep``."""
    step = int(jax.device_get(state.step))
    final = _step_dir(ckpt_dir, step)
    if not is_chief():
        return final
    os.makedirs(ckpt_dir, exist_ok=True)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    host_state = jax.device_get(state)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_state))
    manifest = {
        "step": step,
        "param_bytes": int(sum(
            np.asarray(x).nbytes
            for x in jax.tree_util.tree_leaves(host_state.params))),
        "format": "flax-msgpack-v1",
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    for old in available_steps(ckpt_dir)[:-keep]:
        shutil.rmtree(_step_dir(ckpt_dir, old), ignore_errors=True)
    return final


def restore(ckpt_dir: str, state: Any, step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of ``state`` (a freshly
    created template). ``step=None`` means latest."""
    step = step if step is not None else latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(_step_dir(ckpt_dir, step), "state.msgpack")
    with open(path, "rb") as f:
        host_state = serialization.from_bytes(jax.device_get(state), f.read())

    # Re-place every leaf with the template's sharding (mesh-shape
    # agnostic restore).
    def place(tmpl, host):
        return jax.device_put(host, tmpl.sharding)

    return jax.tree_util.tree_map(place, state, host_state)
