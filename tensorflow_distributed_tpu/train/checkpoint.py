"""Step-tagged checkpoint save/restore.

Replaces the reference's ``tf.train.Supervisor`` checkpointing
(mnist_python_m.py:236-253, SURVEY.md N7) minus its defining bug: the
reference checkpointed to a fresh ``tempfile.mkdtemp()`` (:236), making
cross-run resume impossible by construction (SURVEY.md Appendix B.3).
Here checkpoints go to a durable directory, tagged by step, with
explicit resume.

Design:
- One directory per checkpoint: ``<dir>/step_00001234/`` containing the
  full train-state pytree (params + optimizer state + step) as msgpack
  plus a small JSON manifest. Writes are atomic (tmp dir + rename), so
  a crash mid-save never corrupts the latest checkpoint — the recovery
  story the Supervisor's background saver provided (:245,:252).
- Only the chief process writes (parallel.mesh.is_chief); every process
  restores. Leaves fully addressable on this host come back via
  ``jax.device_get``; leaves sharded ACROSS processes (FSDP over a
  multi-host data axis, cross-process TP) are first allgathered to a
  replicated layout — a collective, so ``save`` must be (and is) called
  by every process, with only the chief writing the bytes. For the
  model sizes this framework targets per-host full gathers are fine;
  sharded per-host saves are an orbax upgrade path documented here.
- Restore places leaves back on the mesh with the *current* state's
  shardings, so a checkpoint saved on one mesh shape restores onto
  another (e.g. train on 8 chips, fine-tune on 1).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, List, Optional

import jax
import numpy as np
from flax import serialization

from tensorflow_distributed_tpu.parallel.mesh import is_chief

_STEP_PREFIX = "step_"


def _identity(a):
    # Module-level so jax.jit's cache keys on ONE function object and
    # hits per (shape, sharding) — a per-call lambda would recompile
    # the allgather for every leaf at every checkpoint.
    return a


def _fetch_host(state: Any, values: bool = True) -> Any:
    """Device->host copy of a state pytree, cross-process-sharding safe.

    A leaf partitioned over an axis that spans processes (FSDP params
    under a multi-host data axis, cross-process TP) is neither fully
    addressable nor fully replicated, so plain ``jax.device_get``
    raises. Such leaves are allgathered to a replicated layout first —
    a COLLECTIVE: every process must reach this call (save/restore are
    structured so they all do). Fully-replicated leaves (the default
    layout) skip the collective and copy from local shards.

    ``values=False``: participate in the collectives (mandatory on
    every process) but skip the host copies — what non-chief processes
    do in ``save``. Returns None.
    """
    if jax.process_count() == 1:
        return jax.device_get(state) if values else None
    from jax.sharding import NamedSharding, PartitionSpec

    def one(x):
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and not x.is_fully_replicated):
            x = jax.jit(_identity,
                        out_shardings=NamedSharding(
                            x.sharding.mesh, PartitionSpec()))(x)
        return jax.device_get(x) if values else None

    out = jax.tree_util.tree_map(one, state)
    return out if values else None


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"{_STEP_PREFIX}{step:08d}")


def available_steps(ckpt_dir: str) -> List[int]:
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if name.startswith(_STEP_PREFIX):
            try:
                out.append(int(name[len(_STEP_PREFIX):]))
            except ValueError:
                continue
    return sorted(out)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def _save_barrier(step: int) -> None:
    """All processes leave ``save`` only after the chief's rename.

    Without this, a same-cluster resume (train -> train(resume=True))
    races the write: non-chief processes could read ``latest_step``
    before the chief finished renaming the new step dir and restore a
    different (older) checkpoint than the chief. Single-process: no-op.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tfd_ckpt_save_{step}")


def save(ckpt_dir: str, state: Any, keep: int = 3) -> str:
    """Write state at its current step; prune to the newest ``keep``.

    Collective under multi-host (every process must call it; only the
    chief writes bytes): cross-process-partitioned leaves are fetched
    via an allgather, and all processes barrier on the completed write
    before returning, so ``latest_step`` is coherent cluster-wide the
    moment ``save`` returns anywhere."""
    step = int(jax.device_get(state.step))
    final = _step_dir(ckpt_dir, step)
    # Collective fetch BEFORE the chief gate: cross-process-partitioned
    # leaves need every process in the allgather. Non-chief processes
    # run the collectives only; the chief also copies values to host.
    host_state = _fetch_host(state, values=is_chief())
    if not is_chief():
        _save_barrier(step)
        return final
    os.makedirs(ckpt_dir, exist_ok=True)
    tmp = final + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(host_state))
    manifest = {
        "step": step,
        "param_bytes": int(sum(
            np.asarray(x).nbytes
            for x in jax.tree_util.tree_leaves(host_state.params))),
        "format": "flax-msgpack-v1",
    }
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if os.path.exists(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    for old in available_steps(ckpt_dir)[:-keep]:
        shutil.rmtree(_step_dir(ckpt_dir, old), ignore_errors=True)
    _save_barrier(step)
    return final


def restore(ckpt_dir: str, state: Any, step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of ``state`` (a freshly
    created template). ``step=None`` means latest."""
    step = step if step is not None else latest_step(ckpt_dir)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {ckpt_dir}")
    path = os.path.join(_step_dir(ckpt_dir, step), "state.msgpack")
    # from_bytes only needs the pytree STRUCTURE (plus leaf shapes for
    # shape-checking) — a zeros skeleton costs no device transfers or
    # collectives, unlike fetching the throwaway template's values.
    skeleton = jax.tree_util.tree_map(
        lambda leaf: np.zeros(leaf.shape, leaf.dtype)
        if isinstance(leaf, jax.Array) else leaf, state)
    with open(path, "rb") as f:
        host_state = serialization.from_bytes(skeleton, f.read())

    # Re-place every leaf with the template's sharding (mesh-shape
    # agnostic restore). Templates sharded across processes can't take
    # a plain device_put of the full host value; each process supplies
    # its addressable shards via the callback form instead.
    def place(tmpl, host):
        if isinstance(tmpl, jax.Array) and not tmpl.is_fully_addressable:
            arr = np.asarray(host)
            return jax.make_array_from_callback(
                arr.shape, tmpl.sharding, lambda idx: arr[idx])
        return jax.device_put(host, tmpl.sharding)

    return jax.tree_util.tree_map(place, state, host_state)
