"""Step-tagged checkpoint save/restore.

Replaces the reference's ``tf.train.Supervisor`` checkpointing
(mnist_python_m.py:236-253, SURVEY.md N7) minus its defining bug: the
reference checkpointed to a fresh ``tempfile.mkdtemp()`` (:236), making
cross-run resume impossible by construction (SURVEY.md Appendix B.3).
Here checkpoints go to a durable directory, tagged by step, with
explicit resume.

Design:
- One directory per checkpoint: ``<dir>/step_00001234/`` containing the
  full train-state pytree (params + optimizer state + step) as msgpack
  plus a small JSON manifest. Writes are atomic (tmp dir + rename), so
  a crash mid-save never corrupts the latest checkpoint — the recovery
  story the Supervisor's background saver provided (:245,:252).
- NATIVE backend (default): only the chief process writes
  (parallel.mesh.is_chief); every process restores. Leaves fully
  addressable on this host come back via ``jax.device_get``; leaves
  sharded ACROSS processes (FSDP over a multi-host data axis,
  cross-process TP) are first allgathered to a replicated layout — a
  collective, so ``save`` must be (and is) called by every process,
  with only the chief writing the bytes. Fine for the model sizes this
  framework targets.
- ORBAX backend (``backend="orbax"`` / ``--checkpoint-backend orbax``,
  the scale path): sharded OCDBT saves — every process writes and
  reads ITS OWN shards, no allgather; completeness is published via a
  chief-written commit marker (see ``_orbax_save``), and ``restore``
  auto-detects which backend wrote a checkpoint.
- Restore places leaves back on the mesh with the *current* state's
  shardings, so a checkpoint saved on one mesh shape restores onto
  another (e.g. train on 8 chips, fine-tune on 1).
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, List, Optional

import jax
import numpy as np
from flax import serialization

from tensorflow_distributed_tpu.observe import goodput as _goodput
from tensorflow_distributed_tpu.observe.registry import emit_event
from tensorflow_distributed_tpu.utils.atomicio import atomic_write_json
from tensorflow_distributed_tpu.parallel.mesh import (
    is_chief, mesh_shape_dict)

_STEP_PREFIX = "step_"
_QUARANTINE_PREFIX = "quarantined_"
_MESH_MANIFEST = "mesh.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed integrity verification (checksum mismatch,
    truncated/undecodable state file). restore() quarantines the
    offender and falls back to the newest verifiable step; this only
    escapes when an EXPLICIT step was requested or no verifiable
    checkpoint remains."""


class MeshMismatchError(RuntimeError):
    """A restore failed because the checkpoint was written on a
    different mesh than the template requests — surfaced with both
    topologies named instead of the opaque orbax/XLA placement error
    underneath. Cross-mesh restore is :func:`restore_resharded`'s job:
    it re-lays the checkpoint out onto the target mesh and verifies
    the resulting layout against the sharding contract."""


def _format_mesh(shape: Optional[dict]) -> str:
    """``data=4,model=2``-style rendering of a mesh-shape dict for
    operator-facing messages (axes of size 1 elided)."""
    if not shape:
        return "unknown mesh"
    parts = [f"{k}={v}" for k, v in shape.items() if int(v) != 1]
    return ",".join(parts) if parts else "single-device"


def _tree_mesh(tree: Any) -> Optional[dict]:
    """The mesh shape a live pytree sits on (first sharded leaf's
    mesh), or None for host trees."""
    for leaf in jax.tree_util.tree_leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        mesh = getattr(sharding, "mesh", None)
        if mesh is not None and getattr(mesh, "shape", None) is not None:
            return mesh_shape_dict(mesh)
    return None


def _mesh_manifest(state: Any) -> Optional[dict]:
    """The mesh/sharding manifest written beside the sha256 manifest:
    mesh axis sizes, process count, device count, and the per-leaf
    PartitionSpecs — everything :func:`restore_resharded` (and an
    operator wondering which steps fit the current topology) needs to
    know about the layout a checkpoint was WRITTEN with. None for a
    state with no sharded leaves (host-only tests)."""
    tree = serialization.to_state_dict(state)
    shape = _tree_mesh(tree)
    if shape is None:
        return None
    from tensorflow_distributed_tpu.analysis.runtime import (
        sharding_spec_strings)
    return {
        "mesh": shape,
        "process_count": int(jax.process_count()),
        "devices": int(np.prod(list(shape.values()))),
        "specs": sharding_spec_strings(tree),
    }


def read_mesh_manifest(ckpt_dir: str, step: int) -> Optional[dict]:
    """The mesh manifest a step was written with, or None (pre-elastic
    checkpoints, unreadable file — absence degrades to 'unknown', it
    never blocks a restore)."""
    path = os.path.join(_step_dir(ckpt_dir, step), _MESH_MANIFEST)
    try:
        with open(path) as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def steps_with_mesh(ckpt_dir: str) -> List[tuple]:
    """``[(step, written-mesh dict or None), ...]`` for every complete
    checkpoint — the operator view of which steps are restorable onto
    which topology (``available_steps`` keeps its plain-int contract
    for the callers that schedule around it)."""
    return [(s, (read_mesh_manifest(ckpt_dir, s) or {}).get("mesh"))
            for s in available_steps(ckpt_dir)]


def _describe_available(ckpt_dir: str, steps: List[int]) -> str:
    """Error-message rendering of the available steps WITH the
    topology each was written on, so the operator can see which are
    restorable onto the current mesh: ``[12, 16] (written on mesh
    data=4)`` when uniform, per-step annotations when mixed."""
    if not steps:
        return "none"
    meta = steps_with_mesh(ckpt_dir)
    meshes = {_format_mesh(m) for _, m in meta if m}
    if not meshes:
        return str(steps)  # pre-elastic checkpoints: no manifests
    if len(meshes) == 1:
        return f"{steps} (written on mesh {meshes.pop()})"
    return "[" + ", ".join(
        f"{s} (mesh {_format_mesh(m)})" if m else str(s)
        for s, m in meta) + "]"


# --- save-I/O retry policy (capped exponential backoff) -----------------
# Module-level so save() call sites don't thread it through; the train
# loop configures it from cfg.resilience at run start.

_io_retries = 2
_io_backoff_s = 0.05
_io_backoff_max_s = 2.0
# Injected write failures (resilience.faults arms these for drills):
# the next N write attempts raise OSError INSIDE the retry loop, so a
# plan with N <= retries proves save-retry recovery end to end.
_injected_io_failures = 0


def set_io_policy(retries: int = 2, backoff_s: float = 0.05,
                  backoff_max_s: float = 2.0) -> None:
    global _io_retries, _io_backoff_s, _io_backoff_max_s
    _io_retries, _io_backoff_s = retries, backoff_s
    _io_backoff_max_s = backoff_max_s


def arm_io_fault(count: int = 1) -> None:
    global _injected_io_failures
    _injected_io_failures = count


def _retry_io(fn, step: int):
    """Run a save-I/O callable with capped-exponential-backoff retries;
    each retry is a recovery event and a goodput count."""
    delay = _io_backoff_s
    for attempt in range(_io_retries + 1):
        try:
            return fn()
        except OSError as e:
            if attempt == _io_retries:
                raise
            emit_event("recovery", kind="ckpt_retry", step=step,
                       attempt=attempt + 1, budget=_io_retries,
                       error=str(e), backoff_s=round(delay, 4))
            _goodput.incr("ckpt_retry")
            time.sleep(delay)
            delay = min(delay * 2, _io_backoff_max_s)


def _identity(a):
    # Module-level so jax.jit's cache keys on ONE function object and
    # hits per (shape, sharding) — a per-call lambda would recompile
    # the allgather for every leaf at every checkpoint.
    return a


def _fetch_host(state: Any, values: bool = True) -> Any:
    """Device->host copy of a state pytree, cross-process-sharding safe.

    A leaf partitioned over an axis that spans processes (FSDP params
    under a multi-host data axis, cross-process TP) is neither fully
    addressable nor fully replicated, so plain ``jax.device_get``
    raises. Such leaves are allgathered to a replicated layout first —
    a COLLECTIVE: every process must reach this call (save/restore are
    structured so they all do). Fully-replicated leaves (the default
    layout) skip the collective and copy from local shards.

    ``values=False``: participate in the collectives (mandatory on
    every process) but skip the host copies — what non-chief processes
    do in ``save``. Returns None.
    """
    if jax.process_count() == 1:
        return jax.device_get(state) if values else None
    from jax.sharding import NamedSharding, PartitionSpec

    def one(x):
        if (isinstance(x, jax.Array) and not x.is_fully_addressable
                and not x.is_fully_replicated):
            x = jax.jit(_identity,
                        out_shardings=NamedSharding(
                            x.sharding.mesh, PartitionSpec()))(x)
        return jax.device_get(x) if values else None

    out = jax.tree_util.tree_map(one, state)
    return out if values else None


def host_step(state: Any) -> int:
    """The state's step counter as a host int, replica-stack safe.

    Local-SGD states carry a replica-stacked step [R] (identical
    values by construction): index BEFORE device_get — an [R] array
    sharded over a cross-process data axis is neither addressable
    nor replicated (the _fetch_host restriction), but the [0]
    indexing op produces a replicated scalar every process can
    read."""
    leaf = state.step
    if getattr(leaf, "ndim", 0):
        leaf = leaf[0]
    return int(jax.device_get(leaf))


def _step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"{_STEP_PREFIX}{step:08d}")


def available_steps(ckpt_dir: str) -> List[int]:
    """COMPLETE checkpoints only: native dirs are atomic (presence
    implies a full state.msgpack), orbax dirs count once the chief's
    commit marker lands — an in-flight or crashed orbax save is
    invisible here, so latest_step never shadows an intact older
    checkpoint.

    Everything else in the directory is ignored by construction:
    ``step_XXXXXXXX.tmp`` staging dirs (crashed mid-write), dirs
    missing both the msgpack and the commit marker, stray non-dir
    files that happen to parse as a step, quarantined_* dirs the
    integrity fallback renamed aside, and any other non-step entry —
    a crashed or corrupt save can never make ``latest_step`` point at
    garbage."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for name in os.listdir(ckpt_dir):
        if not name.startswith(_STEP_PREFIX):
            continue
        try:
            step = int(name[len(_STEP_PREFIX):])
        except ValueError:
            continue  # step_X.tmp staging dirs, misnamed entries
        d = os.path.join(ckpt_dir, name)
        if not os.path.isdir(d):
            continue  # a stray FILE named like a step dir
        if (os.path.exists(os.path.join(d, "state.msgpack"))
                or os.path.exists(os.path.join(d, _ORBAX_MARKER))):
            out.append(step)
    return sorted(out)


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = available_steps(ckpt_dir)
    return steps[-1] if steps else None


def _save_barrier(step: int) -> None:
    """All processes leave ``save`` only after the chief's rename.

    Without this, a same-cluster resume (train -> train(resume=True))
    races the write: non-chief processes could read ``latest_step``
    before the chief finished renaming the new step dir and restore a
    different (older) checkpoint than the chief. Single-process: no-op.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"tfd_ckpt_save_{step}")


_ORBAX_DIRNAME = "orbax"
_ORBAX_MARKER = "ORBAX_COMMITTED"
_orbax_ckptr = None
_orbax_pending: List[tuple] = []  # (ckpt_dir, step, keep) awaiting commit


def _orbax():
    """Lazy singleton StandardCheckpointer (its save is internally
    async; ``orbax_wait`` flushes AND publishes)."""
    global _orbax_ckptr
    if _orbax_ckptr is None:
        import orbax.checkpoint as ocp

        _orbax_ckptr = ocp.StandardCheckpointer()
    return _orbax_ckptr


def _orbax_save(ckpt_dir: str, step: int, state: Any, keep: int,
                background: bool) -> str:
    """Sharded save via orbax (the scale path): every process writes
    ITS OWN shards — no allgather-to-host, no chief gating (orbax
    coordinates the processes itself). Layout:
    ``<dir>/step_xxxxxxxx/orbax/`` plus a chief-written COMMIT MARKER
    file, published only after orbax confirms the write — the step dir
    itself appears early, so ``available_steps`` treats an unmarked
    orbax dir as in-flight/crashed and skips it: a crash mid-save can
    never shadow the intact previous checkpoint, and pruning (also
    deferred to the marker phase) can never delete the last good one.
    restore() auto-detects the layout, so --resume works regardless of
    which backend wrote the checkpoint."""
    # Capture the live state's mesh manifest BEFORE the async write:
    # it publishes with the commit marker in orbax_wait, where the
    # state itself is long gone.
    mesh_manifest = _mesh_manifest(state)
    final = _step_dir(ckpt_dir, step)
    os.makedirs(ckpt_dir, exist_ok=True)
    if background and _orbax_pending:
        # Publish the PREVIOUS background save before scheduling the
        # next (at most one unpublished save in flight — the native
        # writer's bound): without this, markers would only land at
        # the end-of-run wait() and a hard crash mid-training would
        # lose every cadence checkpoint.
        orbax_wait()
    tree = serialization.to_state_dict(state)
    _orbax().save(os.path.join(final, _ORBAX_DIRNAME), tree, force=True)
    _orbax_pending.append((ckpt_dir, step, keep, mesh_manifest))
    if not background:
        orbax_wait()
        _save_barrier(step)
    return final


def orbax_wait() -> None:
    """Flush orbax's internal async write (blocks until every
    process's shards are committed), then publish: the chief writes
    the commit markers and prunes old steps — strictly AFTER the
    commit, so a failed write leaves previous checkpoints untouched
    and unmarked debris behind."""
    global _orbax_pending
    # Pop BEFORE the flush: if the shard write failed, the popped
    # entries are dropped un-marked (correct — they stay invisible
    # debris) instead of being re-published as committed by a later
    # call after the error was already consumed.
    pend, _orbax_pending[:] = _orbax_pending[:], []
    if _orbax_ckptr is not None:
        _orbax_ckptr.wait_until_finished()
    if not is_chief():
        return
    for ckpt_dir, step, keep, mesh_manifest in pend:
        step_path = _step_dir(ckpt_dir, step)
        if mesh_manifest is not None:
            # The mesh manifest lands WITH the commit marker (both
            # chief-written, post-confirmation), so an unmarked crashed
            # save never carries a manifest either.
            atomic_write_json(os.path.join(step_path, _MESH_MANIFEST),
                              mesh_manifest)
        marker = os.path.join(step_path, _ORBAX_MARKER)
        with open(marker, "w"):
            pass
        for old in available_steps(ckpt_dir)[:-keep]:
            shutil.rmtree(_step_dir(ckpt_dir, old), ignore_errors=True)


def _orbax_restore(path: str, state: Any) -> Any:
    """Sharded restore: each process reads its own shards directly into
    the template's shardings — the inverse of the no-allgather save.

    Mirrors _restore_from_raw's compatibility contract: an EMA toggle
    across the save (reconciled via the checkpoint's metadata — newly
    enabled EMA seeds from the restored params, newly disabled drops
    the saved average), and a CLEAR error for replica-stacked vs plain
    shape mismatches (a --param-sync-every flip)."""
    item = os.path.join(path, _ORBAX_DIRNAME)
    tmpl = serialization.to_state_dict(state)
    saved = _orbax().metadata(item).item_metadata.tree

    t_flat = dict(jax.tree_util.tree_flatten_with_path(
        tmpl.get("params", {}))[0])
    s_flat = dict(jax.tree_util.tree_flatten_with_path(
        saved.get("params", {}))[0])
    for pth, leaf in t_flat.items():
        m = s_flat.get(pth)
        if m is not None and tuple(m.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint leaf shape {tuple(m.shape)} != template "
                f"{tuple(np.shape(leaf))} at {jax.tree_util.keystr(pth)};"
                " was this run saved with a different --param-sync-every"
                " (replica-stacked vs plain state)?")

    want_ema = tmpl.get("ema") is not None
    saved_ema = bool(saved.get("ema"))
    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding)
        if isinstance(a, jax.Array) else a, tmpl)
    if want_ema and not saved_ema:
        abstract["ema"] = None          # restore what was saved ...
    if saved_ema and not want_ema:
        # StandardCheckpointer cannot restore a strict subtree (probed:
        # both a missing key and ema=None raise structure-mismatch), so
        # the dropped average is read once and discarded — one extra
        # params-sized read on this rare toggle path.
        abstract["ema"] = abstract["params"]  # ema mirrors params
    restored = _orbax().restore(item, abstract)
    if want_ema and not saved_ema:
        restored["ema"] = restored["params"]  # ... then seed the average
    if saved_ema and not want_ema:
        restored["ema"] = None
    return serialization.from_state_dict(state, restored)


# Single background writer: serializes at most one checkpoint at a
# time (overlapping saves queue), so tmp dirs and pruning never race.
_writer_lock = threading.Lock()
_writer: Optional[concurrent.futures.ThreadPoolExecutor] = None
_pending: List[concurrent.futures.Future] = []


def _write(ckpt_dir: str, step: int, host_state: Any, keep: int,
           mesh_manifest: Optional[dict] = None) -> str:
    """Serialize + atomically publish one checkpoint (chief only).

    The state blob's sha256 is recorded in the manifest next to the
    step metadata and verified on restore — bit rot or a truncated
    write surfaces as :class:`CheckpointCorruptError` (quarantine +
    fallback) instead of silently restoring garbage. The whole I/O
    sequence retries under the capped-backoff policy (serialization
    happens once, outside the retries)."""
    final = _step_dir(ckpt_dir, step)
    blob = serialization.to_bytes(host_state)
    manifest = {
        "step": step,
        "param_bytes": int(sum(
            np.asarray(x).nbytes
            for x in jax.tree_util.tree_leaves(host_state.params))),
        "format": "flax-msgpack-v1",
        "sha256": hashlib.sha256(blob).hexdigest(),
    }

    def attempt() -> None:
        global _injected_io_failures
        if _injected_io_failures > 0:
            _injected_io_failures -= 1
            raise OSError(
                f"injected checkpoint I/O failure at step {step} "
                f"(resilience fault drill)")
        os.makedirs(ckpt_dir, exist_ok=True)
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        with open(os.path.join(tmp, "state.msgpack"), "wb") as f:
            f.write(blob)
        atomic_write_json(os.path.join(tmp, "manifest.json"), manifest)
        if mesh_manifest is not None:
            # Mesh/sharding manifest beside the sha256 manifest: the
            # topology and per-leaf layout the state was WRITTEN with,
            # so restore_resharded (and the operator) can reason about
            # mesh compatibility without decoding the blob.
            atomic_write_json(os.path.join(tmp, _MESH_MANIFEST),
                              mesh_manifest)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)

    _retry_io(attempt, step)
    for old in available_steps(ckpt_dir)[:-keep]:
        shutil.rmtree(_step_dir(ckpt_dir, old), ignore_errors=True)
    return final


@_goodput.accounted("checkpoint")
def save(ckpt_dir: str, state: Any, keep: int = 3,
         background: bool = False, backend: str = "native") -> str:
    """Write state at its current step; prune to the newest ``keep``.

    Goodput: the MAIN-THREAD time spent here (device->host snapshot,
    sync writes, background-queue backpressure) is charged to the
    "checkpoint" category on the active observe.goodput counter; the
    background writer thread's IO overlaps training and is deliberately
    not charged.

    Collective under multi-host (every process must call it; only the
    chief writes bytes): cross-process-partitioned leaves are fetched
    via an allgather, and all processes barrier on the completed write
    before returning, so ``latest_step`` is coherent cluster-wide the
    moment ``save`` returns anywhere.

    ``background=True``: the device->host snapshot still happens here
    (it must — the state is donated/overwritten by the next step, and
    its collectives must stay on the main thread), but serialization
    and the atomic write move to a single writer thread — the
    reference Supervisor's background saver (mnist_python_m.py:245),
    TPU-shaped. No per-save barrier is taken; call ``wait()`` (the
    train loop does, at exit) before relying on ``latest_step``
    cluster-wide. A crash mid-write loses at most that checkpoint —
    the previous one is intact because publication is tmp+rename."""
    step = host_step(state)
    if backend == "orbax":
        return _orbax_save(ckpt_dir, step, state, keep, background)
    if backend != "native":
        raise ValueError(f"unknown checkpoint backend {backend!r}")
    final = _step_dir(ckpt_dir, step)
    # Mesh manifest from the LIVE state (host copies carry no
    # shardings); chief-only like every other native write.
    mesh_manifest = _mesh_manifest(state) if is_chief() else None
    # Collective fetch BEFORE the chief gate: cross-process-partitioned
    # leaves need every process in the allgather. Non-chief processes
    # run the collectives only; the chief also copies values to host.
    host_state = _fetch_host(state, values=is_chief())
    if not is_chief():
        if not background:
            _save_barrier(step)
        return final
    if background:
        global _writer
        with _writer_lock:
            if _writer is None:
                _writer = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="tfd-ckpt")
            prior = [f for f in _pending if not f.done()]
        # Bound the queue to ONE write in flight (outside the lock —
        # wait() needs it): every queued entry pins a full host copy
        # of the state, so an unbounded queue would grow by one model
        # copy per cadence save whenever the disk is slower than the
        # cadence. Blocking here degrades async saving to sync pacing
        # instead of OOMing the chief. Errors stay in the futures for
        # wait() to re-raise.
        if prior:
            concurrent.futures.wait(prior)
        with _writer_lock:
            # Prune futures that completed CLEANLY (failed ones must
            # stay for wait() to re-raise) so _pending doesn't grow by
            # one entry per cadence save over a long run.
            _pending[:] = [f for f in _pending
                           if not f.done() or f.exception() is not None]
            _pending.append(
                _writer.submit(_write, ckpt_dir, step, host_state, keep,
                               mesh_manifest))
        return final
    _write(ckpt_dir, step, host_state, keep, mesh_manifest)
    _save_barrier(step)
    return final


@_goodput.accounted("checkpoint")
def wait() -> None:
    """Block until outstanding background saves land (both the
    native writer thread and orbax's internal async write);
    re-raise the first writer error; barrier so ``latest_step`` is
    coherent cluster-wide afterwards. No-op when nothing is
    pending."""
    with _writer_lock:
        pending, _pending[:] = _pending[:], []
    try:
        first_err = None
        # Orbax flush INSIDE the try: a failed shard write on one
        # process must still fall through to the finally barrier, or
        # the other processes hang waiting for it.
        try:
            orbax_wait()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            first_err = e
        for fut in pending:
            try:
                fut.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                first_err = first_err or e
        if first_err is not None:
            raise first_err  # writer exceptions surface in the caller
    finally:
        # Barrier in a finally, and unconditionally under multi-host:
        # non-chief processes never have pending futures, and a chief
        # that raised must still show up — otherwise the other
        # processes hang in the barrier until the runtime timeout
        # instead of seeing a clean failure. Every process must call
        # wait() (the train loop does).
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tfd_ckpt_flush")


@_goodput.accounted("restore")
def restore_averaged(ckpt_dir: str, state: Any,
                     step: Optional[int] = None) -> Any:
    """Restore a REPLICA-STACKED (local SGD) checkpoint into a PLAIN
    template by averaging the replica dim on host — the mode=eval
    path for local-SGD runs, independent of the evaluating mesh's
    data-axis size (train on 8 replicas, validate on 1). Float
    leaves average; integer leaves (step, opt counters) take
    replica 0 (identical by construction). Both backends' layouts are
    read (native msgpack and orbax OCDBT, auto-detected like
    restore()) — local SGD and sharded checkpointing compose.

    Same integrity contract as restore(): ``step=None`` means the
    newest VERIFIABLE step (a corrupt latest is quarantined with
    fallback to the next-newest); an explicit ``step`` is exact."""
    _warm_runtime()
    steps = available_steps(ckpt_dir)

    def read_raw(s: int):
        return _read_raw(_step_dir(ckpt_dir, s))

    if step is not None:
        if step not in steps:
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {ckpt_dir}; "
                f"available steps: {_describe_available(ckpt_dir, steps)}")
        path, raw = read_raw(step)
    else:
        if not steps:
            raise FileNotFoundError(
                f"no checkpoints under {ckpt_dir} — is this a "
                f"--resume/mode=eval on an empty or absent checkpoint "
                f"dir, or the wrong --checkpoint-dir?")
        last_err: Optional[CheckpointCorruptError] = None
        for s in reversed(steps):
            try:
                path, raw = read_raw(s)
                step = s
                break
            except CheckpointCorruptError as e:
                _quarantine(ckpt_dir, s, str(e))
                last_err = e
        else:
            raise CheckpointCorruptError(
                f"every checkpoint under {ckpt_dir} failed "
                f"verification (all quarantined); last error: "
                f"{last_err}")
    if not (isinstance(raw, dict) and isinstance(raw.get("step"),
                                                 np.ndarray)
            and raw["step"].ndim == 1):
        raise ValueError(
            f"checkpoint at {path} is not replica-stacked (was it "
            "saved with --param-sync-every > 1?)")

    def mean0(x):
        if isinstance(x, np.ndarray) and x.ndim:
            if np.issubdtype(x.dtype, np.floating):
                return x.mean(axis=0)
            return x[0]
        return x

    for key in ("params", "opt_state", "step"):
        if key in raw:
            raw[key] = jax.tree_util.tree_map(mean0, raw[key])
    return _restore_from_raw(raw, state)


def _read_raw(step_path: str):
    """Read one checkpoint's state dict to HOST numpy, either backend
    (orbax OCDBT via the commit marker, else native msgpack with
    checksum verification). Returns (path, raw). Shared by
    restore_averaged and restore_params — the paths that need the raw
    tree rather than a templated restore."""
    opath = os.path.join(step_path, _ORBAX_DIRNAME)
    if os.path.exists(os.path.join(step_path, _ORBAX_MARKER)):
        # Orbax OCDBT layout, detected via the COMMIT MARKER exactly
        # like restore() — a crashed orbax re-save into a dir holding
        # an intact native state.msgpack must fall through to the
        # msgpack, not dispatch onto unmarked shard debris.
        # Template-free restore reads the SAVED tree as host numpy:
        # the shapes come from the checkpoint, which is the point.
        # Warning-free topology safety doesn't apply: host arrays
        # carry no sharding to mismatch.
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return opath, jax.tree_util.tree_map(
                np.asarray, _orbax().restore(opath))
    # Same read+verify path as restore(): a checksum-mismatched or
    # truncated blob raises CheckpointCorruptError.
    return os.path.join(step_path, "state.msgpack"), _load_native_raw(
        step_path)


def _host_finite(tree: Any) -> bool:
    """True when every float leaf of a HOST tree is fully finite."""
    for leaf in jax.tree_util.tree_leaves(tree):
        arr = np.asarray(leaf)
        if (np.issubdtype(arr.dtype, np.floating)
                and not np.isfinite(arr).all()):
            return False
    return True


@_goodput.accounted("restore")
def restore_params(ckpt_dir: str, params: Any,
                   step: Optional[int] = None,
                   prefer_ema: bool = True):
    """PARAMS-ONLY restore for live weight swap: read the newest
    verifiable checkpoint's params (EMA preferred, matching the serve/
    eval restore convention) into the structure and shardings of the
    LIVE ``params`` tree, without touching optimizer state or needing a
    full TrainState template. Returns ``(new_params, step)``.

    The serving engine swaps these in BETWEEN decode steps: same
    shapes/dtypes/shardings as the running params (the engine asserts
    the sharding contract), so the hot decode program is a jit cache
    hit — no drain, no recompile, in-flight KV caches untouched.

    Integrity contract mirrors restore(): ``step=None`` walks back from
    the newest step past anything that fails the sha256/decode check
    (quarantined) or carries NON-FINITE params (skipped with a recovery
    event, NOT quarantined — the bytes are intact and a training-side
    rewind may still want to forensically inspect them); an explicit
    ``step`` is exact and raises instead of recovering around damage.
    Replica-stacked (local SGD) checkpoints are averaged over the
    replica dim, like restore_averaged."""
    _warm_runtime()
    steps = available_steps(ckpt_dir)
    candidates = ([step] if step is not None else list(reversed(steps)))
    if step is not None and step not in steps:
        raise FileNotFoundError(
            f"no checkpoint for step {step} under {ckpt_dir}; "
            f"available steps: {_describe_available(ckpt_dir, steps)}")
    if not steps:
        raise FileNotFoundError(
            f"no checkpoints under {ckpt_dir} — live weight swap needs "
            f"at least one completed save")
    last_err: Optional[Exception] = None
    got = None
    for s in candidates:
        try:
            path, raw = _read_raw(_step_dir(ckpt_dir, s))
        except CheckpointCorruptError as e:
            if step is not None:
                raise
            _quarantine(ckpt_dir, s, str(e))
            last_err = e
            continue
        tree = raw.get("ema") if (prefer_ema and isinstance(raw, dict)
                                  and raw.get("ema") is not None) \
            else raw.get("params") if isinstance(raw, dict) else None
        if tree is None:
            raise ValueError(
                f"checkpoint at {path} carries no params tree")
        if (isinstance(raw.get("step"), np.ndarray)
                and raw["step"].ndim == 1):
            # Replica-stacked local-SGD save: average the replica dim
            # (float leaves mean, ints take replica 0), matching
            # restore_averaged's convention.
            tree = jax.tree_util.tree_map(
                lambda x: (x.mean(axis=0)
                           if np.issubdtype(x.dtype, np.floating)
                           else x[0])
                if isinstance(x, np.ndarray) and x.ndim else x, tree)
        if not _host_finite(tree):
            msg = (f"params at step {s} are non-finite — not a swap "
                   f"target")
            if step is not None:
                raise ValueError(msg)
            emit_event("recovery", kind="swap_skip", step=s,
                       reason="non-finite params")
            last_err = ValueError(msg)
            continue
        got = (s, tree)
        break
    if got is None:
        raise CheckpointCorruptError(
            f"no verifiable swap target under {ckpt_dir}; last error: "
            f"{last_err}")
    s, tree = got
    skeleton = jax.tree_util.tree_map(
        lambda leaf: np.zeros(leaf.shape, leaf.dtype)
        if isinstance(leaf, jax.Array) else leaf, params)
    host = serialization.from_state_dict(skeleton, tree)

    def place(tmpl, val):
        if (isinstance(tmpl, jax.Array)
                and np.shape(val) != tmpl.shape):
            raise ValueError(
                f"checkpoint param shape {np.shape(val)} != live "
                f"{tmpl.shape}: live weight swap needs an identical "
                f"architecture (same config, same sharding)")
        if isinstance(tmpl, jax.Array) and not tmpl.is_fully_addressable:
            arr = np.asarray(val)
            return jax.make_array_from_callback(
                arr.shape, tmpl.sharding, lambda idx: arr[idx])
        return jax.device_put(val, getattr(tmpl, "sharding", None))

    try:
        placed = jax.tree_util.tree_map(place, params, host)
    except ValueError:
        raise  # our own clear shape/architecture messages
    except Exception as e:
        # Same diagnosis as _load_step_checked: a placement failure
        # across a mesh change names both topologies instead of
        # surfacing the runtime's opaque error.
        written = read_mesh_manifest(ckpt_dir, s) or {}
        want = _tree_mesh(params)
        if written.get("mesh") and want and written["mesh"] != want:
            raise MeshMismatchError(
                f"live weight swap from step {s} failed: checkpoint "
                f"written on mesh {_format_mesh(written['mesh'])}, "
                f"live params on mesh {_format_mesh(want)} "
                f"[{type(e).__name__}: {e}]. restore_resharded() "
                f"handles cross-mesh restores for full states.") from e
        raise
    return placed, s


def _plus_zero(tree: Any) -> Any:
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: x + jnp.zeros((), x.dtype), tree)


def launder_buffers(state: Any) -> Any:
    """Rebuild a restored state's arrays through one on-device
    computation (x + 0); shardings propagate elementwise, so the
    layout is unchanged.

    Container-bug workaround, same family as :func:`_warm_runtime`:
    DONATING arrays produced by ``jax.device_put`` into a
    cache-DESERIALIZED executable segfaults this jaxlib's CPU runtime
    (reproduced 6/6 on the in-process rewind path with the persistent
    compile cache on; 0/4 with it off, 2026-08-03). Buffers that came
    out of a jitted computation donate fine, so restore paths that
    feed a donating step launder the state through this identity —
    one extra params-sized device pass per restore, nothing per
    step."""
    return jax.jit(_plus_zero)(state)


_runtime_warmed = False


def _warm_runtime() -> None:
    """Run one trivial jitted executable before the first checkpoint
    read of the process.

    Workaround for a container jaxlib bug (XLA:CPU + the persistent
    compile cache): when the FIRST executable a fresh process loads is
    deserialized from the disk cache after a multi-MB flax msgpack
    restore has churned the heap, the runtime corrupts the allocator
    (`corrupted double-linked list` / `_int_malloc` aborts, ~90%
    reproducible on `--resume`; bisected 2026-08-03 — warm-touching
    the jit machinery first avoids it 100%). Costs one tiny compile
    (~ms, cached); runs AFTER mesh bootstrap because restore does, so
    multi-host backend init order is preserved. No-op after the first
    call or in any process that already ran a jitted computation's
    worth of initialization."""
    global _runtime_warmed
    if _runtime_warmed:
        return
    _runtime_warmed = True
    import jax.numpy as jnp

    jax.jit(lambda x: x + 1)(jnp.zeros(8, jnp.float32)
                             ).block_until_ready()


def _quarantine(ckpt_dir: str, step: int, reason: str) -> str:
    """Rename a corrupt step dir aside (``quarantined_step_XXXXXXXX``)
    so available_steps/latest_step never see it again, preserving the
    bytes for forensics instead of deleting them. Chief-only rename
    (shared FS under multi-host — every process computed the same
    verification verdict from the same bytes, so the fallback order
    agrees)."""
    name = f"{_STEP_PREFIX}{step:08d}"
    dst = os.path.join(ckpt_dir, _QUARANTINE_PREFIX + name)
    # Written-mesh metadata rides the event (read BEFORE the rename):
    # the operator triaging a quarantine sees which topology the bytes
    # belong to, i.e. whether the surviving steps still fit the
    # current mesh.
    written = (read_mesh_manifest(ckpt_dir, step) or {}).get("mesh")
    if is_chief():
        if os.path.exists(dst):
            shutil.rmtree(dst, ignore_errors=True)
        try:
            os.rename(os.path.join(ckpt_dir, name), dst)
        except OSError:
            pass  # already moved/removed — the skip is what matters
    emit_event("recovery", kind="quarantine", step=step, reason=reason,
               mesh=_format_mesh(written) if written else None)
    _goodput.incr("quarantine")
    return dst


def _procs_sync(tag: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def quarantine_from(ckpt_dir: str, step: int, reason: str) -> List[int]:
    """Quarantine every available checkpoint at/after ``step``.

    The rewind policy's companion: a bad update applies at step K but
    is detected a few steps later (the loop retires metrics with lag),
    so cadence saves taken in between hold the POISONED state — their
    bytes are intact (checksums pass) but they must never be a resume
    target. Called before the rewind restore so ``latest_step`` lands
    on the newest pre-damage checkpoint. Returns the quarantined
    steps (chief's view).

    Multi-host protocol: COLLECTIVE — every process must call it.
    Barrier on entry (nobody lists the dir while a previous
    operation's renames are in flight), chief-only renames, barrier
    on exit (the renames are visible on the shared FS before any
    process recomputes ``latest_step``) — so all processes proceed to
    the same restore target."""
    _procs_sync(f"tfd_quarantine_enter_{step}")
    bad: List[int] = []
    if is_chief():
        bad = [s for s in available_steps(ckpt_dir) if s >= step]
        for s in bad:
            _quarantine(ckpt_dir, s, reason)
    _procs_sync(f"tfd_quarantine_exit_{step}")
    return bad


def _load_native_raw(step_path: str) -> Any:
    """Read + VERIFY a native checkpoint's state dict. Raises
    CheckpointCorruptError on unreadable bytes, a manifest-checksum
    mismatch, or an undecodable msgpack blob. Pre-integrity
    checkpoints (no "sha256" in the manifest) skip the checksum and
    still get the decode check."""
    path = os.path.join(step_path, "state.msgpack")
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointCorruptError(f"unreadable {path}: {e}") from e
    expected = None
    man_path = os.path.join(step_path, "manifest.json")
    if os.path.exists(man_path):
        try:
            with open(man_path) as f:
                expected = json.load(f).get("sha256")
        except (OSError, ValueError):
            expected = None  # unreadable manifest: decode check remains
    if expected is not None:
        got = hashlib.sha256(blob).hexdigest()
        if got != expected:
            raise CheckpointCorruptError(
                f"checksum mismatch for {path}: manifest sha256 "
                f"{expected[:12]}…, file {got[:12]}… (truncated or "
                f"bit-flipped write)")
    try:
        return serialization.msgpack_restore(blob)
    except Exception as e:  # msgpack raises library-specific types
        raise CheckpointCorruptError(
            f"undecodable {path}: {e}") from e


def _load_step(ckpt_dir: str, step: int, state: Any) -> Any:
    step_path = _step_dir(ckpt_dir, step)
    if os.path.exists(os.path.join(step_path, _ORBAX_MARKER)):
        # Auto-detect via the COMMIT MARKER (not the orbax subdir):
        # a crashed orbax re-save into a dir holding an intact
        # native state.msgpack must fall through to the msgpack,
        # not dispatch onto incomplete shard debris.
        return _orbax_restore(step_path, state)
    return _restore_from_raw(_load_native_raw(step_path), state)


def _load_step_checked(ckpt_dir: str, step: int, state: Any) -> Any:
    """_load_step with mesh-mismatch diagnosis: a cross-mesh restore
    that dies inside orbax/XLA placement used to surface as that
    library's opaque error — when the written mesh (from the mesh
    manifest) differs from the template's, re-raise as
    :class:`MeshMismatchError` naming both topologies and pointing at
    :func:`restore_resharded`. Errors this layer already makes clear
    (corruption, missing files, shape/param-sync ValueErrors) pass
    through untouched; same-mesh failures are not mesh problems and
    propagate as themselves."""
    try:
        return _load_step(ckpt_dir, step, state)
    except (CheckpointCorruptError, FileNotFoundError, ValueError,
            MeshMismatchError):
        raise
    except Exception as e:
        written = read_mesh_manifest(ckpt_dir, step) or {}
        want = _tree_mesh(state)
        if written.get("mesh") and want \
                and written["mesh"] != want:
            raise MeshMismatchError(
                f"restore of step {step} under {ckpt_dir} failed: the "
                f"checkpoint was written on mesh "
                f"{_format_mesh(written['mesh'])} "
                f"({written.get('process_count', '?')} process(es)) "
                f"but the template requests mesh {_format_mesh(want)} "
                f"[{type(e).__name__}: {e}]. Use restore_resharded() "
                f"to re-lay a checkpoint out onto a different mesh "
                f"with the sharding contract verified.") from e
        raise


@_goodput.accounted("restore")
def restore(ckpt_dir: str, state: Any, step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of ``state`` (a freshly
    created template).

    ``step=None`` means the newest VERIFIABLE step: native checkpoints
    are checksum-verified against their manifest, and a corrupt/
    truncated candidate is quarantined (renamed aside, recovery event
    emitted) with automatic fallback to the next-newest step — a
    damaged latest checkpoint costs `checkpoint_every` steps of
    progress, never the run. An EXPLICIT ``step`` is exact: missing
    raises FileNotFoundError listing the steps actually available;
    corrupt raises CheckpointCorruptError without touching the dir
    (an explicitly requested step is being inspected, not recovered
    around)."""
    _warm_runtime()
    steps = available_steps(ckpt_dir)
    if step is not None:
        if step not in steps:
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {ckpt_dir}; "
                f"available steps: {_describe_available(ckpt_dir, steps)}")
        return _load_step_checked(ckpt_dir, step, state)
    if not steps:
        raise FileNotFoundError(
            f"no checkpoints under {ckpt_dir} — is this a --resume "
            f"on an empty or absent checkpoint dir, or the wrong "
            f"--checkpoint-dir?")
    last_err: Optional[CheckpointCorruptError] = None
    for s in reversed(steps):
        try:
            return _load_step_checked(ckpt_dir, s, state)
        except CheckpointCorruptError as e:
            _quarantine(ckpt_dir, s, str(e))
            last_err = e
    raise CheckpointCorruptError(
        f"every checkpoint under {ckpt_dir} failed verification "
        f"(all quarantined); last error: {last_err}")


@_goodput.accounted("reshard")
def restore_resharded(ckpt_dir: str, state: Any,
                      step: Optional[int] = None,
                      verify: bool = True):
    """Restore a checkpoint written on mesh A into a template laid out
    on mesh B — the elastic-restart path. Returns ``(state, info)``.

    The values are the written ones bit-for-bit (the host round trip
    is layout-free; resharding only changes which device holds which
    slice), re-placed leaf by leaf onto the template's shardings: any
    combination of data/fsdp/tensor axis sizes whose product matches
    the template mesh's devices works, including growing onto MORE
    devices than wrote the checkpoint. ``verify=True`` (default)
    asserts the restored params/EMA against the template's declared
    layout via the sharding-contract checker (analysis/runtime.py) —
    the same contract ``--check`` holds the train step to — so a
    resharded resume starts from a PROVEN layout, not an assumed one.

    ``info`` carries ``step``, ``from_mesh`` (the written manifest's
    topology, None for pre-elastic checkpoints), ``to_mesh``,
    ``resharded`` (False when the topologies match) and ``seconds``
    (the resize window — the wall the goodput ledger charges to the
    "reshard" category). An actual mesh change emits one
    ``kind="reshard_restore"`` recovery event.

    Integrity contract is :func:`restore`'s: ``step=None`` walks back
    from the newest verifiable step; an explicit step is exact."""
    t0 = time.perf_counter()
    restored = restore(ckpt_dir, state, step=step)
    got_step = host_step(restored)
    written = read_mesh_manifest(ckpt_dir, got_step) or {}
    to_mesh = _tree_mesh(state)
    from_mesh = written.get("mesh")
    resharded = bool(from_mesh and to_mesh and from_mesh != to_mesh)
    if verify:
        from tensorflow_distributed_tpu.analysis import (
            runtime as graftcheck)
        graftcheck.assert_sharding_contract(
            restored.params, graftcheck.sharding_tree(state.params),
            what="resharded params")
        if getattr(state, "ema", None) is not None:
            graftcheck.assert_sharding_contract(
                restored.ema, graftcheck.sharding_tree(state.ema),
                what="resharded ema")
    info = {"step": got_step, "from_mesh": from_mesh,
            "to_mesh": to_mesh, "resharded": resharded,
            "seconds": round(time.perf_counter() - t0, 4)}
    if resharded:
        emit_event("recovery", kind="reshard_restore", **info)
        _goodput.incr("reshard_restore")
    return restored, info


def _align_masked_opt(skel: Any, raw: Any) -> Any:
    """Reconcile optax.masked wrappers across a resume: adding/removing
    a weight-decay mask wraps a chain member in MaskedState — an extra
    {"inner_state": ...} level whose own leaves are all empty — so a
    checkpoint written on one side of the change restores on the other
    by inserting/stripping that level to match the template skeleton.
    Purely structural: no array values are invented or dropped."""
    if not (isinstance(skel, dict) and isinstance(raw, dict)):
        return raw
    if (set(skel.keys()) == {"inner_state"}
            and set(raw.keys()) != {"inner_state"}):
        return {"inner_state": _align_masked_opt(skel["inner_state"],
                                                 raw)}
    if (set(raw.keys()) == {"inner_state"}
            and set(skel.keys()) != {"inner_state"}):
        return _align_masked_opt(skel, raw["inner_state"])
    return {k: (_align_masked_opt(skel[k], v) if k in skel else v)
            for k, v in raw.items()}


def _restore_from_raw(raw: Any, state: Any) -> Any:
    """Place a host state-dict into the template's structure and
    shardings (the shared tail of restore/restore_averaged)."""
    # from_state_dict only needs the pytree STRUCTURE (plus leaf shapes
    # for shape-checking) — a zeros skeleton costs no device transfers
    # or collectives, unlike fetching the throwaway template's values.
    skeleton = jax.tree_util.tree_map(
        lambda leaf: np.zeros(leaf.shape, leaf.dtype)
        if isinstance(leaf, jax.Array) else leaf, state)
    # EMA toggled between the saved run and this config must not brick
    # the restore: newly-enabled EMA seeds from the restored params
    # (the natural warm start); newly-disabled EMA drops the average.
    # Checkpoints written before TrainState grew the ema field have no
    # "ema" key at all — from_state_dict would raise on the missing
    # field even with EMA disabled, so absence means "EMA off".
    if isinstance(raw, dict) and isinstance(raw.get("opt_state"),
                                            dict):
        raw["opt_state"] = _align_masked_opt(
            serialization.to_state_dict(state).get("opt_state", {}),
            raw["opt_state"])
    if isinstance(raw, dict) and hasattr(state, "ema"):
        raw.setdefault("ema", None)
        want, have = state.ema is not None, raw["ema"] is not None
        if want and not have:
            raw["ema"] = raw["params"]
        elif have and not want:
            raw["ema"] = None
    host_state = serialization.from_state_dict(skeleton, raw)

    # Re-place every leaf with the template's sharding (mesh-shape
    # agnostic restore). Templates sharded across processes can't take
    # a plain device_put of the full host value; each process supplies
    # its addressable shards via the callback form instead.
    def place(tmpl, host):
        if (isinstance(tmpl, jax.Array)
                and np.shape(host) != tmpl.shape):
            # Catches replica-stacked vs plain state mismatches (a
            # param_sync_every flip across --resume / mode=eval)
            # with a clear error instead of an opaque shard_map
            # shape failure — or silent garbage — downstream.
            raise ValueError(
                f"checkpoint leaf shape {np.shape(host)} != template "
                f"{tmpl.shape}; was this run saved with a different "
                "--param-sync-every (replica-stacked vs plain "
                "state)?")
        if isinstance(tmpl, jax.Array) and not tmpl.is_fully_addressable:
            arr = np.asarray(host)
            return jax.make_array_from_callback(
                arr.shape, tmpl.sharding, lambda idx: arr[idx])
        return jax.device_put(host, tmpl.sharding)

    return jax.tree_util.tree_map(place, state, host_state)
