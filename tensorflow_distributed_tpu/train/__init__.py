"""Training: state, jitted SPMD steps, loop, checkpointing."""

from tensorflow_distributed_tpu.train.state import (  # noqa: F401
    TrainState,
    create_train_state,
)
from tensorflow_distributed_tpu.train.step import (  # noqa: F401
    make_eval_step,
    make_train_step,
)
