"""Task definitions: what varies between model families.

The step/sync machinery (train.step) is task-agnostic; a Task bundles
the loss, the batch shardings/layout, the data streams, and the sample
input used for init. Vision is the reference's task (SURVEY.md §2a);
MLM is the BASELINE.json stretch family.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflow_distributed_tpu.config import TrainConfig
from tensorflow_distributed_tpu.ops.losses import (
    masked_accuracy, masked_softmax_cross_entropy)
from tensorflow_distributed_tpu.parallel.mesh import AXIS_DATA, AXIS_SEQ
from tensorflow_distributed_tpu.train import step as step_lib


@dataclasses.dataclass
class Task:
    """Everything the loop needs beyond the jitted step machinery."""

    name: str
    loss: step_lib.LossFn
    batch_shardings: Any
    sample_input: np.ndarray          # for model.init
    seq_axis: Optional[int]           # batch dim carrying "seq", if any
    train_stream: Callable[[int], Iterator[Any]]  # start_step -> batches
    eval_batches: Callable[[int], Iterator[Any]]  # batch_size -> batches
    eval_size: int                    # rows in the eval split
    steps_per_epoch: int
    # Loss for the EVAL pass; None = same as ``loss``. Train-only
    # regularizers (label smoothing) stay out of reported validation
    # numbers so they're comparable across smoothing settings.
    eval_loss: Optional[step_lib.LossFn] = None
    # The dataset's actual vocabulary (LM tasks) — 0 for vision.
    # train.loop sizes the model's embedding from this for
    # dataset='text', where the tokenizer decides (256 bytes, or
    # whatever the corpus-trained BPE emitted).
    vocab_size: int = 0


# --- vision (the reference's task) --------------------------------------

def make_vision_loss(label_smoothing: float = 0.0):
    """The reference's classification objective (step_lib.loss_fn) with
    a smoothing knob — ONE body, owned by train.step."""
    def vision_loss(apply_fn, params, extra, batch, dropout_key, train):
        return step_lib.loss_fn(apply_fn, params, extra, batch,
                                dropout_key, train,
                                label_smoothing=label_smoothing)

    return vision_loss


vision_loss = step_lib.loss_fn  # unsmoothed default (eval path)


def _make_vision_task(cfg: TrainConfig, mesh: Mesh) -> Task:
    from tensorflow_distributed_tpu.data import ShardedBatcher, load_dataset
    from tensorflow_distributed_tpu.parallel.mesh import process_batch_role

    train_ds, val_ds, _ = load_dataset(cfg.dataset, cfg.data_dir, cfg.seed,
                                       validation_size=cfg.validation_size)
    # Mesh-aware process role, NOT raw process_count: processes sharing
    # a data coordinate must supply identical rows (parallel.mesh).
    n_proc, i_proc = process_batch_role(mesh)
    if cfg.data_backend == "u8_native":
        from tensorflow_distributed_tpu.data.u8 import (
            U8Dataset, U8ShardedBatcher)
        batcher = U8ShardedBatcher(
            U8Dataset.from_float(train_ds), cfg.batch_size,
            cfg.shuffle_seed, num_processes=n_proc,
            process_index=i_proc)
    else:
        batcher = ShardedBatcher(
            train_ds, cfg.batch_size, cfg.shuffle_seed,
            num_processes=n_proc, process_index=i_proc)

    def eval_batches(batch: int) -> Iterator[Any]:
        n = (len(val_ds) // batch) * batch
        for lo in range(0, n, batch):
            yield (val_ds.images[lo:lo + batch], val_ds.labels[lo:lo + batch])

    return Task(
        name="vision", loss=make_vision_loss(cfg.label_smoothing),
        eval_loss=vision_loss,
        batch_shardings=step_lib.default_batch_shardings(mesh),
        sample_input=np.zeros((2,) + train_ds.images.shape[1:], np.float32),
        seq_axis=None, train_stream=batcher.forever,
        eval_batches=eval_batches, eval_size=len(val_ds),
        steps_per_epoch=batcher.steps_per_epoch)


# --- masked LM (BASELINE.json stretch family) ---------------------------

def _fused_lm_metrics(apply_fn, variables, batch, rngs, train,
                      label_smoothing, ce_chunk, mutable=False,
                      ce_impl="scan", mesh=None):
    """Shared fused-CE body (mlm + moe losses): apply in features_only
    mode and run the head matmul inside the chunked loss — the full
    [B, L, V] logits are never materialized (ops/fused_ce.py; the
    Pallas flash-CE triple when ce_impl='kernel').
    Returns (loss, accuracy, mutated_collections)."""
    from tensorflow_distributed_tpu.ops.fused_ce import (
        fused_masked_cross_entropy)
    out = apply_fn(variables, batch["tokens"], train=train, rngs=rngs,
                   mutable=mutable, features_only=True)
    (feats, w, bias, v_axis), mut = out if mutable else (out, {})
    loss, acc = fused_masked_cross_entropy(
        feats, w, bias, batch["targets"], batch["mask"],
        vocab_size=w.shape[v_axis], chunk=ce_chunk,
        label_smoothing=label_smoothing, w_vocab_axis=v_axis,
        impl=ce_impl, mesh=mesh)
    return loss, acc, mut


def make_mlm_loss(label_smoothing: float = 0.0, ce_chunk: int = 0,
                  ce_impl: str = "scan", mesh=None):
    def mlm_loss(apply_fn, params, extra, batch, dropout_key, train):
        """Masked-LM objective over a {tokens, targets, mask} batch."""
        if ce_chunk:
            variables = {"params": params, **extra}
            rngs = {"dropout": dropout_key} if train else {}
            # "health" mirrors apply_model's contract: the transformer
            # blocks' optional activation taps sow into it during
            # training; the step builder pops it out of new_extra into
            # the metrics (train.step._pop_taps).
            mutable = (list(extra) + ["health"]) if train else False
            loss, acc, mut = _fused_lm_metrics(
                apply_fn, variables, batch, rngs, train, label_smoothing,
                ce_chunk, mutable=mutable, ce_impl=ce_impl, mesh=mesh)
            new_extra = dict(mut) if mutable else extra
            return loss, ({"loss": loss, "accuracy": acc}, new_extra)
        logits, new_extra = step_lib.apply_model(
            apply_fn, params, extra, batch["tokens"], dropout_key, train)
        loss = masked_softmax_cross_entropy(logits, batch["targets"],
                                            batch["mask"], label_smoothing)
        metrics = {
            "loss": loss,
            "accuracy": masked_accuracy(logits, batch["targets"],
                                        batch["mask"]),
        }
        return loss, (metrics, new_extra)

    return mlm_loss


mlm_loss = make_mlm_loss()  # default instance (tests, eval)


MOE_AUX_WEIGHT = 0.01  # Switch-Transformer-style coefficient


def make_moe_loss(aux_weight: float = MOE_AUX_WEIGHT,
                  zloss_weight: float = 0.0,
                  label_smoothing: float = 0.0, ce_chunk: int = 0,
                  ce_impl: str = "scan", mesh=None):
    """CLM objective + router losses from the "moe_aux" collection the
    MoeMlp layers sow (models/moe.py): load-balance (weighted by
    ``aux_weight``), router z-loss (``zloss_weight``), and the
    dropped-token fraction (metric only, never in the objective)."""
    from tensorflow_distributed_tpu.models.moe import collect_aux

    def moe_loss(apply_fn, params, extra, batch, dropout_key, train):
        # moe_aux is transient (state.TRANSIENT_COLLECTIONS) — never
        # feed a stale copy back in, or sow would append to it.
        variables = {"params": params,
                     **{k: v for k, v in extra.items() if k != "moe_aux"}}
        rngs = {"dropout": dropout_key} if train else {}
        # "health" rides along like in apply_model so the activation
        # taps (TransformerConfig.health_taps) reach the step builder;
        # harmless at eval (nothing sows without a training pass).
        mutable = ["moe_aux", "health"] if train else ["moe_aux"]
        if ce_chunk:
            loss, acc, mut = _fused_lm_metrics(
                apply_fn, variables, batch, rngs, train, label_smoothing,
                ce_chunk, mutable=mutable, ce_impl=ce_impl,
                mesh=mesh)
        else:
            logits, mut = apply_fn(variables, batch["tokens"], train=train,
                                   rngs=rngs, mutable=mutable)
            loss = masked_softmax_cross_entropy(
                logits, batch["targets"], batch["mask"], label_smoothing)
            acc = masked_accuracy(logits, batch["targets"], batch["mask"])
        aux = collect_aux(mut.get("moe_aux", {}))
        lb = aux.get("load_balance", 0.0)
        z = aux.get("z_loss", 0.0)
        total = loss + aux_weight * lb + zloss_weight * z
        metrics = {
            "loss": loss, "aux_loss": lb, "z_loss": z,
            "dropped_frac": aux.get("dropped_fraction", 0.0),
            "accuracy": acc,
        }
        new_extra = extra
        if "health" in mut:
            # Sown activation taps ride new_extra to the step builder
            # (train.step._pop_taps strips them back out — they never
            # persist into TrainState like moe_aux never does).
            new_extra = {**extra, "health": mut["health"]}
        return total, (metrics, new_extra)

    return moe_loss


moe_loss = make_moe_loss()  # default-weight instance (tests, eval)


def mlm_batch_shardings(mesh: Mesh) -> Dict[str, NamedSharding]:
    """Tokens shard batch over "data" and sequence over "seq" — the
    long-context layout the ring attention consumes without resharding."""
    s = NamedSharding(mesh, P(AXIS_DATA, AXIS_SEQ))
    return {"tokens": s, "targets": s, "mask": s}


def _make_lm_task(cfg: TrainConfig, mesh: Mesh, objective: str,
                  seq_len: int = 128, vocab_size: int = 64) -> Task:
    """Shared LM task body; ``objective``: "mlm" (masked positions) or
    "clm" (next-token), with a "moe_" prefix selecting the MoE-aware
    loss (masked CE + router losses). All use the {tokens, targets,
    mask} layout — what differs is the data generator and the model's
    attention direction (TransformerConfig.causal).

    ``cfg.seq_len`` / ``cfg.synthetic_vocab`` override the defaults —
    the long-context path (--seq-len 8192 --mesh.seq 8) flows through
    here into the stream AND (via train.loop) the model's max_len."""
    from tensorflow_distributed_tpu.data.lm import (
        LmBatcher, synthetic_clm, synthetic_mlm)

    seq_len = cfg.seq_len or seq_len
    vocab_size = cfg.synthetic_vocab or vocab_size

    if cfg.dataset == "text":
        # Causal LM over a LOCAL file (data.lm.text_clm): the real-
        # corpus path, no egress. The tokenizer decides the vocab —
        # 256 byte values, or the corpus-trained BPE's actual size —
        # and train.loop sizes the model from Task.vocab_size.
        if not objective.endswith("clm"):
            raise ValueError(
                "dataset='text' is causal-LM only (gpt_lm / moe_lm / "
                "pipelined_lm); bert_mlm has no byte-masking stream")
        from tensorflow_distributed_tpu.data.lm import text_clm
        train_ds, val_ds = text_clm(cfg.data_dir, seq_len=seq_len,
                                    seed=cfg.seed,
                                    tokenizer=cfg.text_tokenizer,
                                    bpe_vocab_size=cfg.bpe_vocab_size)
        # Fail at task creation, not after training: the final eval
        # needs >= one data-axis-wide batch of val rows, and the
        # batcher needs a full train batch.
        data_size = dict(mesh.shape).get(AXIS_DATA, 1)
        if len(train_ds) < cfg.batch_size or len(val_ds) < data_size:
            raise ValueError(
                f"corpus {cfg.data_dir!r} too small: {len(train_ds)} "
                f"train / {len(val_ds)} val windows of seq_len "
                f"{seq_len}; need >= batch_size {cfg.batch_size} train "
                f"and >= mesh data axis {data_size} val")
    elif cfg.dataset not in ("mnist", "synthetic", "cifar10",
                             "cifar10_synthetic", "imagenet_synthetic"):
        # LM families ignore the vision dataset names (synthetic token
        # streams stand in), but an unknown value is far more likely a
        # typo for "text" than a request for synthetic data.
        raise ValueError(
            f"unknown dataset {cfg.dataset!r} for an LM family; use "
            f"'text' (byte-level corpus from --data-dir) or leave the "
            f"default for the synthetic token stream")
    else:
        gen = (synthetic_mlm if objective.endswith("mlm")
               else synthetic_clm)
        n = max(16 * cfg.batch_size, 4096)
        train_ds = gen(n=n, seq_len=seq_len, vocab_size=vocab_size,
                       seed=cfg.seed)
        val_ds = gen(n=max(4 * cfg.eval_batch_size, 512),
                     seq_len=seq_len, vocab_size=vocab_size,
                     seed=cfg.seed + 1)
    from tensorflow_distributed_tpu.parallel.mesh import process_batch_role

    n_proc, i_proc = process_batch_role(mesh)
    batcher = LmBatcher(train_ds, cfg.batch_size, cfg.shuffle_seed,
                        num_processes=n_proc, process_index=i_proc)

    def eval_batches(batch: int) -> Iterator[Any]:
        nrows = (len(val_ds) // batch) * batch
        for lo in range(0, nrows, batch):
            yield val_ds.batch(np.arange(lo, lo + batch))

    moe = objective.startswith("moe_")
    return Task(
        name=objective,
        loss=(make_moe_loss(cfg.moe_aux_weight, cfg.moe_zloss_weight,
                            cfg.label_smoothing, ce_chunk=cfg.ce_chunk,
                            ce_impl=cfg.ce_impl, mesh=mesh)
              if moe else make_mlm_loss(cfg.label_smoothing,
                                        ce_chunk=cfg.ce_chunk,
                                        ce_impl=cfg.ce_impl, mesh=mesh)),
        # Eval drops the train-only smoothing but keeps the router
        # terms (they're part of the MoE objective being reported) —
        # and keeps the fused head: if ce_chunk is what makes the
        # train shapes fit, the dense eval logits would OOM at the
        # same shapes (metrics parity is pinned in tests). Always the
        # scan formulation: the eval batch is clamped from the val
        # split, so its per-device token count can fail the Pallas
        # kernel's shape gate that the train shapes pass.
        eval_loss=(make_moe_loss(cfg.moe_aux_weight, cfg.moe_zloss_weight,
                                 ce_chunk=cfg.ce_chunk, mesh=mesh)
                   if moe else make_mlm_loss(ce_chunk=cfg.ce_chunk,
                                             mesh=mesh)),
        batch_shardings=mlm_batch_shardings(mesh),
        # Init executes the forward; ring attention's shard_map needs
        # the sample batch divisible by the data axis.
        sample_input=np.zeros(
            (max(2, dict(mesh.shape).get(AXIS_DATA, 1)), seq_len),
            np.int32), seq_axis=1,
        train_stream=batcher.forever, eval_batches=eval_batches,
        eval_size=len(val_ds), steps_per_epoch=batcher.steps_per_epoch,
        vocab_size=train_ds.vocab_size)


def make_task(cfg: TrainConfig, mesh: Mesh) -> Task:
    """Model family -> task. bert_mlm trains masked-LM, gpt_lm trains
    next-token; everything else is image classification."""
    moe = cfg.moe_experts > 0
    if cfg.model == "bert_mlm":
        # The moe objective is masked-CE + router losses — it works for
        # the MLM data stream too; only the generator differs.
        return _make_lm_task(cfg, mesh, "moe_mlm" if moe else "mlm")
    if cfg.model in ("gpt_lm", "pipelined_lm"):
        return _make_lm_task(cfg, mesh, "moe_clm" if moe else "clm")
    if cfg.model == "moe_lm":
        return _make_lm_task(cfg, mesh, "moe_clm")
    return _make_vision_task(cfg, mesh)
