"""The training loop: the reference's five entrypoints as one function.

Covers mnist_python_m.py:285-320 (train loop + validation loop) and
mnist_single.py:104-134 (single-device loop + timing prints) with the
same code on any mesh shape and any task family. The loop body is thin
by design — the only per-step host work is feeding the next prefetched
batch, exactly the collapse SURVEY.md §3.5 prescribes.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, Optional

import jax
import numpy as np

from tensorflow_distributed_tpu.analysis import runtime as graftcheck
from tensorflow_distributed_tpu.config import TrainConfig
from tensorflow_distributed_tpu.data import prefetch_to_mesh
from tensorflow_distributed_tpu.models import build_model
from tensorflow_distributed_tpu.observe import Observatory
from tensorflow_distributed_tpu.observe import health as health_mod
from tensorflow_distributed_tpu.observe.registry import host_tags
from tensorflow_distributed_tpu.parallel import make_mesh
from tensorflow_distributed_tpu.parallel.mesh import (
    bootstrap, is_chief, mesh_shape_dict)
from tensorflow_distributed_tpu.parallel.sharding import (
    process_slice, shard_batch)
from tensorflow_distributed_tpu.resilience.faults import (
    FaultPlan, parse_fault_plan)
from tensorflow_distributed_tpu.resilience.policies import (
    LossSpikeDetector, NonFinitePolicy, RecoveryBudgetExceeded)
from tensorflow_distributed_tpu.resilience.watchdog import Watchdog
from tensorflow_distributed_tpu.train import checkpoint as ckpt
from tensorflow_distributed_tpu.train.optim import make_optimizer
from tensorflow_distributed_tpu.train.preemption import PreemptionGuard
from tensorflow_distributed_tpu.train.state import (
    TrainState, create_train_state, param_count)
from tensorflow_distributed_tpu.train.step import make_eval_step, make_train_step
from tensorflow_distributed_tpu.train.tasks import Task, make_task
from tensorflow_distributed_tpu.utils.logging import MetricLogger, Timer
from tensorflow_distributed_tpu.utils.profiling import StepProfiler


@dataclasses.dataclass
class TrainResult:
    state: TrainState
    train_seconds: float
    eval_seconds: float
    final_metrics: Dict[str, float]
    steps_per_sec: float
    images_per_sec: float
    logger: MetricLogger


def evaluate(state: TrainState, eval_fn, task: Task, mesh, batch: int
             ) -> Dict[str, float]:
    """Full-split eval in fixed-size SPMD batches (the reference's 5x1000
    validation loop, mnist_python_m.py:309-320, as jitted calls)."""
    data_size = mesh.shape["data"]
    # Clamp to the split size (rounded to a shardable multiple) so a
    # small validation split with a large eval_batch still evaluates.
    batch = min(batch, (task.eval_size // data_size) * data_size)
    if batch == 0:
        raise ValueError(
            f"validation split ({task.eval_size} rows) smaller than the "
            f"mesh data axis ({data_size})")
    totals: Dict[str, float] = {}
    count = 0
    for host_batch in task.eval_batches(batch):
        # eval_batches yields the same full batch on every process;
        # shard_batch wants process-local rows under multi-host (mesh-
        # aware: co-data-coordinate processes keep identical slices).
        b = shard_batch(mesh, process_slice(host_batch, mesh),
                        seq_axis=task.seq_axis)
        # The totals reduce on host per eval batch by design; this loop
        # runs only on the eval cadence (and at the end), never per
        # train step.
        # graftcheck: disable=host-sync-in-loop -- eval fetch, cadence-gated
        m = jax.device_get(eval_fn(state, b))
        for k, v in m.items():
            totals[k] = totals.get(k, 0.0) + float(v) * batch
        count += batch
    out = {k: v / max(count, 1) for k, v in totals.items()}
    if "loss" in out and task.name.endswith("clm"):
        # exp of the AVERAGED cross-entropy (not an average of
        # per-batch exponentials) — the standard LM eval number.
        # CLM only: its batches weight every token equally, so the
        # row-weighted batch average IS the token average; MLM's
        # per-batch masked-token counts vary, which would make this
        # a mean-of-means pseudo-perplexity — omitted rather than
        # reported subtly wrong.
        out["perplexity"] = float(np.exp(out["loss"]))
    if count < task.eval_size and is_chief():
        # Fixed-size SPMD batches truncate the split to a batch multiple
        # (exact for the reference's 5x1000 split) — surface the tail
        # drop instead of silently skewing small-split accuracy.
        print(f"[eval] split has {task.eval_size} rows; evaluated "
              f"{count} (remainder dropped by batch size {batch})")
    return out


def _build_model_and_state(cfg: TrainConfig, mesh, task):
    """Shared model/optimizer/state construction for train and eval."""
    size_kw = {"size": cfg.model_size} if cfg.model_size else {}
    if (cfg.remat != "none"
            and cfg.model in ("bert_mlm", "gpt_lm", "moe_lm",
                              "pipelined_lm")):
        size_kw.update(remat=True, remat_policy=cfg.remat)
    if cfg.moe_experts > 0:  # validated: transformer families only
        size_kw["moe_experts"] = cfg.moe_experts
    if cfg.model == "moe_lm" or cfg.moe_experts > 0:
        size_kw["moe_top_k"] = cfg.moe_top_k
        size_kw["moe_capacity_factor"] = cfg.moe_capacity_factor
        size_kw["moe_group_len"] = cfg.moe_group_len
        size_kw["moe_dispatch"] = cfg.moe_dispatch
    if cfg.model in ("bert_mlm", "gpt_lm", "moe_lm", "pipelined_lm"):
        # Transformer-family knobs, shared by the pipelined variant
        # (rope positions are derived inside its stage_fn; tying is
        # local to its embedding shell — models/pipelined.py).
        if cfg.pos_emb != "learned":
            size_kw["pos_emb"] = cfg.pos_emb
            size_kw["rope_theta"] = cfg.rope_theta
        if cfg.tie_embeddings:
            size_kw["tie_embeddings"] = cfg.tie_embeddings
        if cfg.shard_vocab:
            size_kw["shard_vocab"] = cfg.shard_vocab
        if cfg.n_kv_heads:
            size_kw["n_kv_heads"] = cfg.n_kv_heads
        if cfg.attn_window:
            size_kw["attn_window"] = cfg.attn_window
        if cfg.kv_cache_quant != "none":
            size_kw["kv_cache_quant"] = cfg.kv_cache_quant
        if cfg.mlp_variant != "gelu":
            size_kw["mlp_variant"] = cfg.mlp_variant
        if cfg.norm != "layernorm":
            size_kw["norm"] = cfg.norm
        if cfg.dataset == "text":
            # The model vocab follows the TOKENIZER: 256 byte values,
            # or whatever the corpus-trained BPE actually emitted
            # (task.vocab_size reads the built dataset — tiny corpora
            # can train fewer merges than requested).
            size_kw["vocab_size"] = task.vocab_size
        elif cfg.synthetic_vocab:
            size_kw["vocab_size"] = cfg.synthetic_vocab
        if cfg.seq_len:
            # The model's position budget tracks the training window —
            # the knob that makes long context trainable from the CLI
            # (ring attention engages via mesh.seq; the data stream
            # gets the same length through train.tasks).
            size_kw["max_len"] = cfg.seq_len
    if (cfg.observe.health and cfg.observe.health_taps
            and cfg.model in ("bert_mlm", "gpt_lm", "moe_lm")):
        # Activation-RMS taps in the transformer blocks (config
        # rejects the pipelined combination — no sow path out of its
        # manual shard_map).
        size_kw["health_taps"] = True
    if cfg.model == "pipelined_lm":
        size_kw["num_microbatches"] = cfg.pipeline_microbatches
        if cfg.pipeline_virtual_stages > 1:
            size_kw["virtual_stages"] = cfg.pipeline_virtual_stages
    model_mesh = mesh
    if cfg.grad_sync != "implicit":
        # The explicit grad-sync step (parallel/overlap.py) runs the
        # forward INSIDE a shard_map over the whole mesh, where a
        # with_sharding_constraint on already-manual axes is an error:
        # build the model mesh-less (no activation pins, no TP
        # metadata — config.validate has already pinned the mesh to
        # pure-data, so both were no-ops anyway).
        model_mesh = None
        if cfg.model in ("bert_mlm", "gpt_lm", "moe_lm"):
            size_kw["tp_partitioning"] = False
    model = build_model(
        cfg.model, mesh=model_mesh, dropout_rate=cfg.dropout_rate,
        init_scheme=cfg.init_scheme,
        compute_dtype=jax.numpy.bfloat16
        if cfg.compute_dtype == "bfloat16" else jax.numpy.float32,
        **size_kw)
    tx = make_optimizer(cfg)
    state = create_train_state(model, tx, task.sample_input, mesh, cfg.seed,
                               fsdp=cfg.param_partition == "fsdp",
                               opt_fsdp=cfg.param_partition == "zero1",
                               ema=cfg.ema_decay > 0)
    return model, state


def evaluate_only(cfg: TrainConfig,
                  logger: Optional[MetricLogger] = None) -> Dict[str, float]:
    """mode=eval: restore a checkpoint, run the full validation pass,
    report. The reference could only reach its validation loop by
    training first (mnist_python_m.py:309-320 is the tail of main());
    here a saved run is re-validated — or validated on a different
    mesh shape — without a single training step.
    """
    cfg.validate()  # enforces checkpoint_dir for mode="eval"
    bootstrap()
    logger = logger or MetricLogger(enabled=is_chief(),
                                max_records=cfg.observe.max_records)
    mesh = make_mesh(cfg.mesh)
    task = make_task(cfg, mesh)
    _, state = _build_model_and_state(cfg, mesh, task)
    # mode=eval usually re-validates an EXISTING run: when the JSONL
    # already holds that run's records, append the eval record to the
    # artifact instead of truncating the training history away. A
    # fresh path still gets created (and reruns onto it replace).
    import os
    obs = Observatory(cfg.observe, chief=is_chief(),
                      tags=host_tags(mesh, cfg),
                      process_index=jax.process_index(),
                      append=bool(cfg.observe.metrics_jsonl
                                  and os.path.exists(
                                      cfg.observe.metrics_jsonl)))
    try:
        if cfg.param_sync_every > 1:
            # Local-SGD checkpoints persist the replica stack; average
            # it ON HOST into the plain template, so validation works on
            # ANY mesh shape regardless of the training replica count
            # (the documented eval-on-a-different-mesh capability).
            with obs.phase("restore"):
                state = ckpt.restore_averaged(cfg.checkpoint_dir, state)
        else:
            with obs.phase("restore"):
                state = ckpt.restore(cfg.checkpoint_dir, state)
        step = int(jax.device_get(state.step))
        eval_fn = make_eval_step(mesh, loss=task.eval_loss or task.loss,
                                 batch_shardings=task.batch_shardings)
        with obs.phase("eval"), Timer() as eval_t:
            metrics = evaluate(state, eval_fn, task, mesh,
                               cfg.eval_batch_size)
        logger.log_json({
            "event": "eval", "step": step,
            "eval_seconds": round(eval_t.elapsed, 3),
            **{f"val_{k}": round(v, 5) for k, v in metrics.items()},
        })
        obs.emit("eval", step=step,
                 eval_seconds=round(eval_t.elapsed, 3),
                 **{f"val_{k}": round(v, 5) for k, v in metrics.items()})
    finally:
        obs.close()
    return metrics


@dataclasses.dataclass
class _GenTask:
    """The two Task fields _build_model_and_state reads — enough to
    size the model for mode=generate without building (and paying, and
    being gated by) the full training data pipeline."""

    vocab_size: int
    sample_input: np.ndarray


def generate_only(cfg: TrainConfig,
                  logger: Optional[MetricLogger] = None) -> Dict:
    """mode=generate: restore a checkpoint and continue a prompt.

    The product surface over models/generate.py: greedy / sampled
    (gen_temperature, gen_top_k, gen_top_p) or beam search (num_beams),
    on the EMA weights when the checkpoint tracks them (the same
    Polyak preference eval applies). For dataset=text the prompt is a
    string run through the SAME tokenizer as training
    (data/lm.py::text_codec) and the continuation is decoded back;
    otherwise the prompt is comma-separated token ids. No reference
    counterpart (the reference has no sequence models, SURVEY.md §5).
    """
    cfg.validate()
    bootstrap()
    logger = logger or MetricLogger(enabled=is_chief(),
                                max_records=cfg.observe.max_records)
    mesh = make_mesh(cfg.mesh)

    # Tokenizer/vocab WITHOUT building the training task: make_task
    # would re-encode and window the whole corpus (and reject one
    # smaller than batch_size — a training-side check generation has
    # no use for). The checkpoint pins the model shapes, so the vocab
    # just has to match what training used.
    dec = None
    if cfg.dataset == "text":
        from tensorflow_distributed_tpu.data.lm import text_codec
        enc, dec, vocab = text_codec(cfg.data_dir, cfg.text_tokenizer,
                                     cfg.bpe_vocab_size)
        ids = enc(cfg.prompt)
        if not ids:
            raise ValueError(f"prompt {cfg.prompt!r} encoded to zero "
                             f"tokens")
    else:
        vocab = cfg.synthetic_vocab or 64
        try:
            ids = [int(t) for t in cfg.prompt.split(",")]
        except ValueError:
            raise ValueError(
                f"prompt {cfg.prompt!r} is not comma-separated token "
                f"ids (string prompts need dataset=text, whose "
                f"tokenizer defines a text vocabulary)") from None
        # Bound-checked below against the BUILT model's vocab — when
        # synthetic_vocab is unset, _build_model_and_state leaves the
        # family default (e.g. 50257 for gpt_lm small), so ids in
        # [synthetic default, family vocab) are legal model inputs.

    seq = cfg.seq_len or 128
    shim = _GenTask(vocab_size=vocab, sample_input=np.zeros(
        (max(2, dict(mesh.shape).get("data", 1)), seq), np.int32))
    model, state = _build_model_and_state(cfg, mesh, shim)
    if cfg.dataset != "text":
        bad = [t for t in ids if not 0 <= t < model.cfg.vocab_size]
        if bad:
            # The embedding gather would silently CLAMP these.
            raise ValueError(
                f"prompt ids {bad} outside the model vocabulary "
                f"[0, {model.cfg.vocab_size})")
    if cfg.param_sync_every > 1:
        state = ckpt.restore_averaged(cfg.checkpoint_dir, state)
    else:
        state = ckpt.restore(cfg.checkpoint_dir, state)
    params = state.params if state.ema is None else state.ema

    # Replicated global placement: every process holds the same
    # cfg.prompt, so this is multi-host-safe where a host-local numpy
    # array into the jitted prefill is not.
    from jax.sharding import NamedSharding, PartitionSpec as P
    prompt = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P()), np.asarray(ids, np.int32)[None, :])

    from tensorflow_distributed_tpu.models.generate import (
        beam_search, generate)
    if cfg.num_beams > 1:
        seqs, scores = beam_search(model, params, prompt,
                                   cfg.max_new_tokens,
                                   num_beams=cfg.num_beams)
        out = jax.device_get(seqs)[0, 0]          # best beam
        score = float(jax.device_get(scores)[0, 0])
    else:
        key = (jax.random.key(cfg.seed)
               if cfg.gen_temperature > 0 else None)
        out = jax.device_get(generate(
            model, params, prompt, cfg.max_new_tokens,
            temperature=cfg.gen_temperature, top_k=cfg.gen_top_k,
            top_p=cfg.gen_top_p, key=key))[0]
        score = None
    new_tokens = [int(i) for i in out]
    rec = {"event": "generate", "step": int(jax.device_get(state.step)),
           "prompt": cfg.prompt, "new_tokens": new_tokens}
    if score is not None:
        rec["beam_score"] = round(score, 5)
    if dec is not None:
        rec["text"] = dec(new_tokens)
    logger.log_json(rec)
    return rec


def train(cfg: TrainConfig, logger: Optional[MetricLogger] = None
          ) -> TrainResult:
    cfg.validate()
    bootstrap()
    plan_rec = None
    if cfg.plan == "auto":
        # Cost-model auto-layout (analysis/planner): score every valid
        # mesh x strategy by AOT-compiling the real step, then rewrite
        # cfg's mesh/partition to the winner BEFORE the mesh is built.
        # Re-validate after the rewrite so the chosen combination
        # passes the same rules an explicit one would; the record is
        # emitted once the Observatory exists below.
        from tensorflow_distributed_tpu.analysis.planner.plan import (
            apply_auto)
        plan_rec = apply_auto(cfg)
        # The plan is applied: cfg now IS an explicit config (the
        # "plan" record below keeps the audit trail), so clear the
        # planner flags before re-validating — the parse-time
        # "planner owns the mesh" guard must not reject its own
        # choice, and the budget knob is validated against plan=auto
        # (it already did its job inside apply_auto).
        cfg.plan = ""
        cfg.plan_hbm_budget_gb = 0.0
        if not cfg.profile_dir:
            # Consumed by apply_auto; with a profile window it also
            # feeds the device-time prediction join, so keep it then.
            cfg.plan_calibration = ""
        cfg.validate()
    logger = logger or MetricLogger(enabled=is_chief(),
                                max_records=cfg.observe.max_records)
    mesh = make_mesh(cfg.mesh)
    task = make_task(cfg, mesh)
    model, state = _build_model_and_state(cfg, mesh, task)
    n_params = param_count(state.params)  # before replica stacking
    # The run's observability hub: metrics registry (JSONL/CSV sinks),
    # host-phase Chrome trace, step-time breakdown, throughput/MFU
    # accounting, goodput ledger. Inert unless cfg.observe configures
    # an output. Constructing it installs the goodput counter that
    # train.checkpoint / train.preemption charge blocked time to.
    # Built BEFORE local-SGD replica stacking so the FLOPs estimate
    # counts the model once, not once per replica.
    obs = Observatory.for_training(cfg, mesh, task=task, model=model,
                                   params=state.params,
                                   chief=is_chief())
    # Everything below runs under the Observatory: close() must
    # fire on EVERY exit (normal, preemption, halt_on_nonfinite,
    # eval failure) so sinks flush (the CSV only writes on close),
    # file handles drop, and the process-global goodput counter is
    # uninstalled rather than left charging a dead run.
    try:
        if plan_rec is not None:
            # The auto-layout choice, durable next to the run's own
            # records: what was chosen, what it predicted, how many
            # candidates competed (observe.report's "Plan" section).
            logger.log_json({"event": "plan", **plan_rec})
            obs.emit("plan", **plan_rec)
        local_sgd = cfg.param_sync_every > 1
        if local_sgd:
            from tensorflow_distributed_tpu.train.local_sgd import (
                averaged_view, stack_state)
            # Replica-stacked state from here on; checkpoints persist
            # the stack (exact divergence survives resume), evals and
            # the returned result use the averaged view.
            state = stack_state(state, mesh)
            view = averaged_view
        else:
            view = lambda s: s  # noqa: E731

        start_step = 0
        if cfg.resume and ckpt.latest_step(cfg.checkpoint_dir) is not None:
            latest = ckpt.latest_step(cfg.checkpoint_dir)
            written = (ckpt.read_mesh_manifest(cfg.checkpoint_dir,
                                               latest)
                       or {}).get("mesh")
            current = mesh_shape_dict(mesh)
            resumed_extra = {}
            if written and written != current:
                # Elastic resume: the checkpoint was written on a
                # DIFFERENT mesh (a supervisor --elastic restart after
                # device loss, or an operator growing the run onto
                # returned capacity). Restore through the resharded
                # path — layout re-derived onto this mesh and verified
                # against the sharding contract — and charge the
                # resize window to its own goodput category. The
                # global batch is unchanged; the data layer re-derives
                # the per-device share from the new data-axis width,
                # so the loss trajectory stays comparable across the
                # resize.
                with obs.phase("reshard"):
                    state, rinfo = ckpt.restore_resharded(
                        cfg.checkpoint_dir, state)
                    state = ckpt.launder_buffers(state)
                resumed_extra = {
                    "from_mesh": rinfo["from_mesh"],
                    "to_mesh": rinfo["to_mesh"],
                    "reshard_seconds": rinfo["seconds"],
                    "per_device_batch":
                        cfg.batch_size // current["data"]}
            else:
                with obs.phase("restore"):
                    state = ckpt.restore(cfg.checkpoint_dir, state)
                    # The restored buffers feed a DONATING step; see
                    # checkpoint.launder_buffers for the container bug
                    # this sidesteps.
                    state = ckpt.launder_buffers(state)
            start_step = ckpt.host_step(state)
            logger.log_json({"event": "resumed", "step": start_step,
                             **resumed_extra})
            obs.emit("resumed", step=start_step, **resumed_extra)

        # Resilience wiring (all off by default — see config.
        # ResilienceConfig and the resilience/ package): fault plan,
        # non-finite policy, spike detector, watchdog, save-retry
        # policy. Built AFTER the Observatory so recovery events from
        # the library layers reach the run's sinks.
        res = cfg.resilience
        plan = (parse_fault_plan(res.fault_plan) if res.fault_plan
                else FaultPlan())
        plan.bind(start_step)
        policy = (NonFinitePolicy(res.nonfinite, res.max_skips,
                                  res.max_rewinds)
                  if res.nonfinite != "off" else None)
        spikes = (LossSpikeDetector(res.spike_window, res.spike_factor)
                  if res.spike_window else None)
        wdog = (Watchdog(res.data_timeout_s, res.sync_timeout_s)
                if (res.data_timeout_s or res.sync_timeout_s) else None)
        ckpt.set_io_policy(res.save_retries, res.save_retry_backoff_s)

        # ZeRO-1 needs new_params constrained back to the params' OWN
        # state-creation layout after the slot-sharded update — captured
        # from the live arrays so pipe/TP-sharded params keep those axes
        # (a blanket "replicated" would clobber them).
        params_out = (jax.tree_util.tree_map(lambda a: a.sharding,
                                             state.params)
                      if cfg.param_partition == "zero1" else None)
        # On-device health telemetry cadence (observe/health.py): the
        # vitals ride the log-cadence metrics fetch, so the default
        # cadence IS log_every (health_every must be a multiple —
        # config.validate enforces it).
        health_every = 0
        if cfg.observe.health:
            health_every = cfg.observe.health_every or cfg.log_every
        if cfg.model == "pipelined_lm" and cfg.pipeline_schedule == "1f1b":
            from tensorflow_distributed_tpu.train.pipeline_step import (
                make_1f1b_train_step)
            step_fn = make_1f1b_train_step(model, mesh, cfg.seed,
                                           batch_shardings=task.batch_shardings,
                                           moe_aux_weight=cfg.moe_aux_weight,
                                           moe_zloss_weight=cfg.moe_zloss_weight,
                                           grad_norm_metric=cfg.log_grad_norm,
                                           label_smoothing=cfg.label_smoothing,
                                           ema_decay=cfg.ema_decay,
                                           backward=cfg.pipeline_backward,
                                           ce_chunk=cfg.ce_chunk,
                                           params_out_shardings=params_out,
                                           health_every=health_every)
        elif local_sgd:
            from tensorflow_distributed_tpu.train.local_sgd import (
                make_local_sgd_train_step)
            step_fn = make_local_sgd_train_step(
                mesh, cfg.param_sync_every, cfg.seed, loss=task.loss,
                batch_shardings=task.batch_shardings,
                grad_norm_metric=cfg.log_grad_norm)
        else:
            step_fn = make_train_step(
                mesh, cfg.seed, loss=task.loss,
                batch_shardings=task.batch_shardings,
                accum_steps=cfg.grad_accum_steps,
                grad_norm_metric=cfg.log_grad_norm,
                ema_decay=cfg.ema_decay,
                params_out_shardings=params_out,
                skip_nonfinite=(policy is not None
                                and policy.mode == "skip_batch"),
                health_every=health_every,
                grad_sync=cfg.grad_sync,
                state_template=(state if cfg.grad_sync != "implicit"
                                else None),
                grad_sync_bucket_bytes=(
                    int(cfg.grad_sync_bucket_mb * 2 ** 20)
                    if cfg.grad_sync_bucket_mb else 0),
                grad_clip_norm=cfg.grad_clip_norm or 0.0)
            if cfg.grad_sync == "overlap":
                # Surface the per-step collective-traffic estimate so
                # the step records can split comm into exposed vs
                # hidden (observe/hub.py). The step carries the exact
                # plan its compiled program executes.
                from tensorflow_distributed_tpu.parallel import overlap
                plan_b = step_fn.bucket_plan
                obs.note_grad_sync(overlap.comm_bytes_per_step(plan_b),
                                   plan_b.describe())
        eval_fn = make_eval_step(mesh, loss=task.eval_loss or task.loss,
                                 batch_shardings=task.batch_shardings)
        # 1F1B-recompute steps advertise their extra executed FLOPs
        # (hw-MFU next to model MFU — train.pipeline_step).
        obs.note_step_fn(step_fn, params=state.params,
                         model_cfg=getattr(model, "cfg", None))
        logger.log_json({
            "event": "start", "model": cfg.model, "task": task.name,
            "params": n_params, "mesh": dict(mesh.shape),
            "global_batch": cfg.batch_size, "start_step": start_step,
        })
        # Lifecycle events go to BOTH outputs on purpose: logger owns
        # the human stdout stream (and needs no observe config), obs
        # owns the tagged file sinks (mesh/config_hash ride its tags).
        obs.emit("start", model=cfg.model, task=task.name, params=n_params,
                 global_batch=cfg.batch_size, start_step=start_step)

        def make_iterator(from_step: int):
            """Task stream -> fault wrapping -> prefetch; rebuilt on a
            rewind so the replayed steps consume the batches the
            uninterrupted run would have (fault events are one-shot,
            so an injected NaN is not re-injected on replay)."""
            return prefetch_to_mesh(
                plan.wrap_stream(task.train_stream(from_step),
                                 from_step),
                mesh, seq_axis=task.seq_axis)

        it = make_iterator(start_step)

        def _fetch(step_id: int):
            plan.maybe_stall(step_id)  # injected stalls happen INSIDE
            #                            the watched fetch
            return next(it)

        def cadence(step_now: int, state: TrainState, metrics) -> None:
            """Periodic log/eval/checkpoint — applied to EVERY step
            including the warm-up compile step."""
            if cfg.log_every and step_now % cfg.log_every == 0:
                # graftcheck: disable=host-sync-in-loop -- the log fetch,
                # gated on log_every by the line above
                host_metrics = jax.device_get(metrics)
                # Health scalars travel in the SAME fetch but are
                # per-module records, not step-log columns: split them
                # off so stdout stays readable, and emit them only
                # when the device's cadence flag says they're real
                # (observe/health.py).
                host_metrics, health, health_emitted = health_mod.split(
                    host_metrics)
                if health_emitted and health:
                    for module, fields in health_mod.group(health):
                        obs.emit("health", step=step_now, module=module,
                                 **{k: round(v, 8)
                                    for k, v in fields.items()})
                logger.log(step_now, **host_metrics)
                obs.log_step(step_now, host_metrics)
                if cfg.halt_on_nonfinite and not np.isfinite(
                        float(host_metrics["loss"])):
                    # Flush queued async saves first so the named resume
                    # point is the TRUE latest (metrics are replicated, so
                    # every process raises here and reaches wait()'s
                    # barrier).
                    ckpt.wait()
                    raise FloatingPointError(
                        f"non-finite loss {host_metrics['loss']} at step "
                        f"{step_now} (halt_on_nonfinite=true); last durable "
                        f"checkpoint: "
                        f"{ckpt.latest_step(cfg.checkpoint_dir) if cfg.checkpoint_dir else None}")
            if cfg.eval_every and step_now % cfg.eval_every == 0:
                with obs.phase("eval"):
                    em = evaluate(view(state), eval_fn, task, mesh,
                                  cfg.eval_batch_size)
                logger.log(step_now, **{f"val_{k}": v for k, v in em.items()})
                obs.emit("eval", step=step_now,
                         **{f"val_{k}": float(v) for k, v in em.items()})
            if (cfg.checkpoint_dir and cfg.checkpoint_every
                    and step_now % cfg.checkpoint_every == 0):
                if plan:
                    # An armed ckpt_io_fail@step_now fires inside this
                    # save's retry loop.
                    plan.arm_checkpoint_faults(step_now)
                with obs.phase("checkpoint"):
                    ckpt.save(cfg.checkpoint_dir, state, cfg.keep_checkpoints,
                              background=cfg.checkpoint_async,
                              backend=cfg.checkpoint_backend)

        def _inspect(step_id: int, step_metrics) -> Optional[int]:
            """Policy check on one RETIRED step's metrics (already
            device-synced — the host read costs nothing extra).
            Returns the bad step id when the policy orders a rewind;
            raises on halt / budget exhaustion; None otherwise. Inert
            (no host fetch at all) when no policy or detector is
            configured."""
            if policy is None and spikes is None:
                return None
            if policy is None and cfg.log_every \
                    and step_id % cfg.log_every:
                # Spike detection WITHOUT a recovery policy is advisory
                # telemetry: sample it on the log cadence instead of
                # paying a per-step host fetch in the hot path. The
                # trade is real and deliberate: a spike shorter than
                # log_every can fall between samples, and the rolling
                # window arms over window*log_every steps — acceptable
                # for an advisory signal. A run that ACTS on losses
                # (resilience.nonfinite != off) keeps full per-step
                # inspection; set log_every=1 to sample every step.
                return None
            # One transfer for both policy scalars (loss + the step's
            # skip flag) instead of two round trips. The jitted step
            # can skip on a non-finite GRAD NORM while the loss stays
            # finite (backward-only overflow); the skipped_nonfinite
            # metric it reports is the authority, so those skips charge
            # the budget exactly like NaN losses.
            # graftcheck: disable=host-sync-in-loop -- per-step by the
            # policy contract; _sync_retired already retired these
            # arrays, so this is a scalar D2H copy, not a device stall
            host_loss, host_skipped = map(float, jax.device_get(
                (step_metrics["loss"],
                 step_metrics.get("skipped_nonfinite", 0.0))))
            device_skipped = host_skipped > 0
            if not np.isfinite(host_loss) or device_skipped:
                if policy is None:
                    return None  # legacy path: cadence halt (or not)
                action = policy.on_nonfinite(step_id, host_loss)
                if action == "halt":
                    # Flush queued async saves first so the named
                    # resume point is the TRUE latest.
                    ckpt.wait()
                    raise RecoveryBudgetExceeded(policy.halt_message(
                        step_id, host_loss,
                        ckpt.latest_step(cfg.checkpoint_dir)
                        if cfg.checkpoint_dir else None))
                if action == "skip":
                    # The jitted step already discarded the update on
                    # device; here we only count it.
                    obs.goodput.incr("skip_nonfinite")
                    return None
                return step_id
            if spikes is not None:
                med = spikes.observe(host_loss)
                if med is not None:
                    if policy is not None:
                        action = policy.on_spike(step_id, host_loss,
                                                 med)
                        if action == "halt":
                            # Rewind budget exhausted on a spike:
                            # same ending as the nonfinite path —
                            # a swallowed halt would train on the
                            # diverged run unbounded.
                            ckpt.wait()
                            raise RecoveryBudgetExceeded(
                                policy.halt_message(
                                    step_id, host_loss,
                                    ckpt.latest_step(cfg.checkpoint_dir)
                                    if cfg.checkpoint_dir else None))
                        if action == "rewind":
                            return step_id
                    else:
                        obs.emit("recovery", kind="loss_spike",
                                 step=step_id,
                                 loss=round(host_loss, 6),
                                 window_median=round(med, 6))
            return None

        def _sync_retired(sid: int, m) -> None:
            """The one retirement sync protocol, shared by the main
            loop and the trailing drain (watchdog deadline when
            configured, plain block otherwise)."""
            if wdog is not None:
                wdog.sync(m, sid)
            else:
                # graftcheck: disable=host-sync-in-loop -- THE designed
                # retirement point: the bounded in-flight window blocks
                # on the oldest pending step on purpose (see the deque
                # comment below); everything else overlaps with it
                jax.block_until_ready(m)

        def _rewind(cur_state, bad_step: int):
            """In-process recovery: flush the writer, quarantine every
            checkpoint saved at/after the bad update (detection lags
            retirement by the in-flight window, so cadence saves in
            between hold the POISONED state — intact bytes, damaged
            values), restore the newest verifiable pre-damage
            checkpoint (corrupt candidates are quarantined by
            ckpt.restore itself), and hand back the step to re-enter
            the loop from. The poisoned live state is only a placement
            template for the restore."""
            # Drain the device FIRST: steps dispatched after the bad
            # one are still executing, and interleaving their
            # completion with the restore's device_puts + the replay's
            # fresh dispatch trips the container XLA:CPU runtime's
            # heap (same class as the async-ckpt SIGSEGV the repo
            # already documents). A rewind is off the hot path; a full
            # quiesce costs nothing that matters.
            # graftcheck: disable=host-sync-in-loop -- deliberate full
            # quiesce on the cold recovery path (see comment above)
            jax.block_until_ready(cur_state.params)
            ckpt.wait()
            ckpt.quarantine_from(
                cfg.checkpoint_dir, bad_step,
                reason=f"saved at/after non-finite step {bad_step} "
                       f"(rewind)")
            with obs.phase("rewind"):
                # The save at bad_step - 1 is usually clean (step K's
                # loss comes from the params ENTERING K, i.e. update
                # K-1's output — batch-caused NaNs never touch it),
                # but when the damage IS in the params (backward-only
                # overflow at K-1), that checkpoint holds intact
                # bytes around poisoned values. So verify each
                # candidate's params are finite after restoring and
                # walk back until one is — never quarantining a clean
                # sole checkpoint on a mere suspicion, never
                # restoring a poisoned one and burning the budget on
                # an instant re-NaN.
                # Hoisted OUT of the walk-back loop (graftcheck
                # jit-in-loop): one verify program, reused for every
                # candidate checkpoint instead of a fresh trace +
                # compile per iteration.
                params_finite = jax.jit(
                    lambda p: jax.numpy.all(jax.numpy.array(
                        [jax.numpy.all(jax.numpy.isfinite(x))
                         for x in jax.tree_util.tree_leaves(p)])))
                while True:
                    target = ckpt.latest_step(cfg.checkpoint_dir)
                    if target is None:
                        raise FloatingPointError(
                            "resilience.nonfinite=rewind: non-finite "
                            f"loss at step {bad_step} with no finite "
                            "durable checkpoint before it — nothing "
                            "to rewind to (checkpoint_dir="
                            f"{cfg.checkpoint_dir!r}, checkpoint_"
                            f"every={cfg.checkpoint_every})")
                    new_state = ckpt.restore(cfg.checkpoint_dir,
                                             cur_state)
                    # graftcheck: disable=host-sync-in-loop -- the
                    # walk-back must read each candidate's verdict on
                    # host; rewind is the cold recovery path
                    finite = bool(jax.device_get(
                        params_finite(new_state.params)))
                    if finite:
                        break
                    ckpt.quarantine_from(
                        cfg.checkpoint_dir, target,
                        reason=f"restored params non-finite (damage "
                               f"predates step {target})")
                new_state = ckpt.launder_buffers(new_state)
            rewound_to = ckpt.host_step(new_state)
            obs.goodput.incr("rewind")
            logger.log_json({"event": "rewound", "step": rewound_to})
            obs.emit("recovery", kind="rewind", from_step=bad_step,
                     to_step=rewound_to)
            if spikes is not None:
                spikes.reset()  # replayed steps re-approach the spike
            return new_state, rewound_to

        # --check (graftcheck's runtime layer): snapshot the layout the
        # state was CREATED with — the declared sharding contract the
        # first step must hand back (analysis/runtime.py).
        declared_shardings = (graftcheck.sharding_tree(state.params)
                              if cfg.check else None)

        # Warm-up compile outside the timed steady-state span (the
        # reference's timings conflated graph setup with steps; ours don't).
        # Goodput charges it as "compile" — setup, not forward progress.
        metrics = None
        want_rewind = None  # bad step id when a rewind is ordered
        with Timer() as compile_t:
            if cfg.train_steps > start_step:
                # Signal faults scheduled for the warm-up step fire
                # here like any other step's would (the guard isn't
                # armed yet, so a sigterm@first-step drill is a hard
                # first-leg crash — which is what it models).
                plan.maybe_device_loss(start_step + 1,
                                       cfg.checkpoint_dir)
                plan.maybe_signal(start_step + 1)
                with obs.phase("compile"):
                    # The first fetch is the one most likely to wedge
                    # (cold source, first NFS touch) — watch it too.
                    batch0 = (wdog.fetch(
                        lambda: _fetch(start_step + 1), start_step + 1)
                        if wdog is not None else _fetch(start_step + 1))
                    state, metrics = step_fn(state, batch0)
                    jax.block_until_ready(metrics)
                if declared_shardings is not None:
                    # The first step's output is where a missing
                    # with_sharding_constraint first shows: GSPMD
                    # propagating an input sharding into the params
                    # re-lays-out every later step silently.
                    graftcheck.assert_sharding_contract(
                        state.params, declared_shardings, what="params")
                cadence(start_step + 1, state, metrics)
                want_rewind = _inspect(start_step + 1, metrics)
        steps_done = 1 if cfg.train_steps > start_step else 0

        # Bounded async dispatch: block on the oldest pending step once more
        # than 2 ride in the deque, so at most 2 unconfirmed steps trail the
        # current dispatch (3 in flight at the dispatch instant). Unbounded
        # dispatch can queue dozens of SPMD programs whose collectives then
        # compete for the same worker threads (on oversubscribed hosts the
        # XLA:CPU rendezvous aborts after 40s); a shallow window preserves
        # the host/device overlap that hides dispatch latency.
        inflight = collections.deque()
        profiler = StepProfiler(
            log_dir=cfg.profile_dir if is_chief() else "",
            start_step=cfg.profile_start_step,
            num_steps=cfg.profile_num_steps)

        # SIGTERM (preemption notice) -> stop at a coordinated safe step,
        # fall through to the final durable save below, exit 0 for the
        # scheduler to restart with --resume. Only armed when there is a
        # checkpoint dir to save into.
        guard = PreemptionGuard(enabled=bool(cfg.checkpoint_dir))
        try:
            # --check: every transfer in the steady-state loop is
            # explicit by design (prefetch device_puts, cadence
            # device_gets); an IMPLICIT one is a bug the guard turns
            # into an error at its source line. Transparent when off.
            with graftcheck.transfer_guard(cfg.check), \
                    Timer() as train_t:
                # The outer while exists for ONE flow: a policy-ordered
                # rewind restores a checkpoint in-process and re-enters
                # the step loop from the restored step. Every other
                # exit (completion, preemption, halt) leaves it on the
                # first pass; without resilience configured the body is
                # the plain single-pass loop it always was.
                next_start = start_step + steps_done
                while True:
                    if want_rewind is not None:
                        # The restore inside _rewind does implicit
                        # transfers by design (checkpoint._warm_runtime,
                        # launder_buffers) — exempt the cold recovery
                        # path from the steady-state --check guard or a
                        # rewind under --check would crash instead of
                        # recovering.
                        with graftcheck.transfer_allowed(cfg.check):
                            state, next_start = _rewind(state,
                                                        want_rewind)
                        it = make_iterator(next_start)
                        want_rewind = None
                    for i in range(next_start, cfg.train_steps):
                        if guard.should_stop(i):
                            logger.log_json({"event": "preempted",
                                             "step": i})
                            obs.instant("preempted", step=i)
                            obs.emit("preempted", step=i)
                            break
                        plan.maybe_device_loss(i + 1,
                                               cfg.checkpoint_dir)
                        plan.maybe_signal(i + 1)
                        profiler.observe(i + 1, pending=metrics)
                        with obs.data():
                            batch = (wdog.fetch(lambda: _fetch(i + 1),
                                                i + 1)
                                     if wdog is not None
                                     else _fetch(i + 1))
                        with obs.dispatch():
                            state, metrics = step_fn(state, batch)
                        inflight.append((i + 1, metrics))
                        if len(inflight) > 2:
                            sid, m = inflight.popleft()
                            with obs.device_wait():
                                _sync_retired(sid, m)
                            verdict = _inspect(sid, m)
                            if verdict is not None:
                                want_rewind = verdict
                                inflight.clear()
                                break
                        cadence(i + 1, state, metrics)
                        obs.step_end()
                    if want_rewind is not None:
                        continue
                    if guard.fired is None:
                        # Retire the trailing in-flight steps through
                        # the same policy checks (a NaN on the final
                        # steps must not slip out unhandled); inert
                        # without a policy/detector.
                        while inflight:
                            sid, m = inflight.popleft()
                            _sync_retired(sid, m)
                            verdict = _inspect(sid, m)
                            if verdict is not None:
                                want_rewind = verdict
                                inflight.clear()
                                break
                        if want_rewind is not None:
                            continue
                    break
                jax.block_until_ready(state.params)
        finally:
            # Always restore the prior SIGTERM disposition — an exception
            # escaping the loop must not leave a handler that absorbs
            # future SIGTERMs into an Event nobody reads. The profiler
            # likewise: an open trace window must be finalized even when
            # the loop raises (halt_on_nonfinite fires mid-cadence — the
            # diverging run's trace is exactly the one worth keeping), and
            # the host-phase Chrome trace is flushed durable for the same
            # reason (the JSONL sink already flushes per record).
            guard.close()
            profiler.stop(pending=metrics)
            if profiler.captured:
                # Ground truth beside the predictions: parse the
                # closed window's Perfetto export and emit one
                # device_time record per attributed program
                # (observe/xprof.py; explicit-null on absent or
                # unusable profiler data).
                obs.emit_device_time(cfg.profile_dir,
                                     calibration=cfg.plan_calibration)
            obs.flush()
            if wdog is not None:
                wdog.close()

        preempted = guard.fired is not None
        if preempted and cfg.checkpoint_dir:
            # The eviction grace window exists for THIS save: take it
            # before eval, which on a real validation split could outlive
            # the grace period and void the whole feature. Goodput charges
            # the whole preempted flush as "drain" (the nested checkpoint
            # accounting suppresses itself inside an outer category).
            with obs.phase("drain"):
                ckpt.save(cfg.checkpoint_dir, state, cfg.keep_checkpoints,
                          background=cfg.checkpoint_async,
                          backend=cfg.checkpoint_backend)
                ckpt.wait()
        state_out = view(state)
        with Timer() as eval_t:
            if preempted:
                final = {}
            else:
                with obs.phase("eval"):
                    final = evaluate(state_out, eval_fn, task, mesh,
                                     cfg.eval_batch_size)
        if cfg.checkpoint_dir and not preempted:
            # The final save rides the SAME path as cadence saves: under
            # checkpoint_async a cadence save of this very step may still
            # sit in the writer queue, and the single writer serializes
            # them; a synchronous bypass here would race it on the tmp
            # dir. wait() then flushes the queue and barriers so
            # latest_step is coherent on return.
            with obs.phase("checkpoint"):
                ckpt.save(cfg.checkpoint_dir, state, cfg.keep_checkpoints,
                          background=cfg.checkpoint_async,
                          backend=cfg.checkpoint_backend)
                ckpt.wait()

        # Steps ACTUALLY executed in the timed span (a preemption break
        # runs fewer than the configured horizon; reporting the horizon
        # would inflate throughput).
        steady_steps = max(
            int(jax.device_get(state_out.step)) - start_step - steps_done, 0)
        sps = steady_steps / train_t.elapsed if train_t.elapsed > 0 else 0.0
        result = TrainResult(
            state=state_out,
            train_seconds=compile_t.elapsed + train_t.elapsed,
            eval_seconds=eval_t.elapsed, final_metrics=final,
            steps_per_sec=sps, images_per_sec=sps * cfg.batch_size,
            logger=logger)
        logger.log_json({
            "event": "done", "steps": int(jax.device_get(state_out.step)),
            "train_seconds": round(result.train_seconds, 3),
            "compile_seconds": round(compile_t.elapsed, 3),
            "steps_per_sec": round(sps, 3),
            "images_per_sec": round(result.images_per_sec, 1),
            **{f"val_{k}": round(v, 5) for k, v in final.items()},
        })
        if plan_rec is not None:
            # Predicted -> measured drift for the auto-layout choice:
            # the cost model's error on THIS run, durable next to the
            # plan record it audits (and the signal a calibration
            # refit consumes). Emitted only when the run measured a
            # steady-state p50.
            measured = obs.steptime.summary().get("step_ms_p50")
            pred = plan_rec.get("predicted_step_ms")
            if (isinstance(measured, (int, float))
                    and isinstance(pred, (int, float)) and pred > 0):
                obs.emit("plan_drift", predicted_step_ms=pred,
                         measured_step_ms_p50=round(measured, 4),
                         drift_ratio=round(measured / pred, 4),
                         calibration_id=plan_rec.get("calibration_id"))
        # Final rollup: rolling step-time stats + goodput ledger (counted
        # since the Observatory was built — restores, compile, eval and
        # checkpoint stalls all charged) + steady-state throughput/MFU.
        obs.summarize(
            steps=int(jax.device_get(state_out.step)),
            preempted=preempted,
            train_seconds=round(result.train_seconds, 3),
            compile_seconds=round(compile_t.elapsed, 3),
            steps_per_sec=round(sps, 3),
            **obs.accountant.rates(steady_steps * obs.items_per_step,
                                   train_t.elapsed),
            **{f"val_{k}": round(v, 5) for k, v in final.items()})
        return result
    finally:
        obs.close()
