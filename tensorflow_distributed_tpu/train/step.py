"""The jitted SPMD train/eval steps — the core of the framework.

This single function replaces the reference's entire per-step distributed
machinery (SURVEY.md N3-N5, N12, N14, N15):

    reference (per sync step, over gRPC/TCP)          here (on-chip)
    ------------------------------------------        ----------------
    workers pull full weights from ps                 params already resident
    each worker: forward/backward                     same, per mesh slice
    workers push grads to ps accumulators             XLA psum over ICI
    ps waits for replicas_to_aggregate=2, means       mean is the psum, sync
    ps ApplyAdam, bumps global_step                   optax update + step+1
    token queue releases workers                      nothing to release

Synchronous-by-construction: there are no accumulators, stale-gradient
drops, token queues, or chief queue-runner threads
(mnist_python_m.py:210-233, :279-282) because SPMD has no asynchrony to
police. Loss is the mean over the *global* batch, which is exactly
SyncReplicasOptimizer's mean-of-replica-gradients semantics (mean of
per-shard means over equal shards == global mean).

The same compiled step runs on a 1-device mesh (the mnist_single.py
path) and an N-device mesh — BASELINE.json's north star.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.observe import health as observe_health
from tensorflow_distributed_tpu.ops.losses import accuracy, softmax_cross_entropy
from tensorflow_distributed_tpu.parallel.sharding import (
    FSDP_MIN_SIZE, batch_sharding, replicated)
from tensorflow_distributed_tpu.train.state import TrainState, ema_update
from tensorflow_distributed_tpu.utils import prng

Batch = Any  # task-defined pytree; classification default: (images, labels)
Metrics = Dict[str, jax.Array]
# A LossFn maps (apply_fn, params, extra, batch, dropout_key, train) ->
# (scalar loss, (metrics dict, new_extra)). ``extra`` carries non-param
# variable collections (BatchNorm stats); stat-free tasks pass {} through
# unchanged. Tasks (vision, masked-LM, ...) plug in here; the step/sync
# machinery below is task-agnostic.
LossFn = Callable


def apply_model(apply_fn: Callable, params: Any, extra: Any, inputs: Any,
                dropout_key: jax.Array, train: bool) -> Tuple[jax.Array, Any]:
    """Run the model forward, updating mutable collections when training.

    Returns (outputs, new_extra). BatchNorm batch means/variances are
    computed over the *global* (sharded) batch inside jit, so XLA inserts
    the cross-replica stats allreduce automatically — the SPMD analog of
    synchronized BatchNorm.

    Training passes also open the transient "health" collection so the
    transformer blocks' optional activation-RMS taps (``health_taps``,
    observe/health.py) can sow; models without taps sow nothing and
    the collection never materializes. When present it rides
    ``new_extra`` to the step builder, which folds it into the metrics
    (``_pop_taps``) — it is never fed back into the model.
    """
    variables = {"params": params, **extra}
    rngs = {"dropout": dropout_key} if train else {}
    mutable = (list(extra) + ["health"]) if train else False
    if mutable:
        out, new_vars = apply_fn(variables, inputs, train=train, rngs=rngs,
                                 mutable=mutable)
        return out, dict(new_vars)
    return apply_fn(variables, inputs, train=train, rngs=rngs), extra


def loss_fn(apply_fn: Callable, params: Any, extra: Any, batch: Batch,
            dropout_key: jax.Array, train: bool,
            label_smoothing: float = 0.0
            ) -> Tuple[jax.Array, Tuple[Metrics, Any]]:
    """Default classification loss — the reference's task
    (mnist_python_m.py:205-207)."""
    images, labels = batch
    logits, new_extra = apply_model(apply_fn, params, extra, images,
                                    dropout_key, train)
    loss = softmax_cross_entropy(logits, labels, label_smoothing)
    metrics = {"loss": loss, "accuracy": accuracy(logits, labels)}
    return loss, (metrics, new_extra)


def default_batch_shardings(mesh: Mesh):
    return (batch_sharding(mesh, 4), batch_sharding(mesh, 1))


def _pop_taps(metrics: Metrics, new_extra: Any) -> Tuple[Metrics, Any]:
    """Fold the sown "health" collection (activation-RMS taps) out of
    the forward's mutated collections and into the metrics dict — the
    taps are per-step telemetry, not state, and must never persist
    into TrainState.extra (state.TRANSIENT_COLLECTIONS agrees)."""
    if isinstance(new_extra, dict) and "health" in new_extra:
        new_extra = dict(new_extra)
        taps = new_extra.pop("health")
        metrics = dict(metrics,
                       **observe_health.flatten_taps(taps))
    return metrics, new_extra


def make_train_step(mesh: Mesh, seed: int = 0, donate: bool = True,
                    loss: LossFn = loss_fn,
                    batch_shardings: Any = None,
                    accum_steps: int = 1,
                    jit: bool = True,
                    grad_norm_metric: bool = False,
                    ema_decay: float = 0.0,
                    params_out_shardings: Any = None,
                    skip_nonfinite: bool = False,
                    health_every: int = 0,
                    grad_sync: str = "implicit",
                    state_template: Any = None,
                    grad_sync_bucket_bytes: int = 0,
                    grad_sync_min_size: int = 0,
                    grad_clip_norm: float = 0.0
                    ) -> Callable[[TrainState, Batch],
                                  Tuple[TrainState, Metrics]]:
    """Build the jitted train step for a mesh.

    Gradient synchronization is implicit by default: params are
    replicated (or partition-annotated) and the batch is sharded over
    the data axis, so XLA's SPMD partitioner inserts the psum allreduce
    in the backward pass — the explicit, inspectable shard_map/psum
    formulation lives in ``parallel.collectives`` and is proven
    equivalent in tests.

    ``grad_sync`` != "implicit" dispatches to the EXPLICIT collective
    step (parallel.overlap): "overlap" buckets the grad tree,
    reduce-scatters each bucket over the data axis, applies the ZeRO-1
    sharded optimizer update per bucket, and all-gathers updated params
    bucketed so XLA can hide the collectives under backward compute;
    "serial" is the same skeleton with one monolithic pmean (the A/B
    baseline). Requires ``state_template`` (the state the loop threads
    — it pins the slot shardings the sharded update runs against) and
    a pure-data mesh; ``grad_sync_bucket_bytes``/``grad_sync_min_size``
    forward the bucket bound and the scatterable-leaf threshold (0 =
    the overlap module's defaults). ``accum_steps`` must stay 1 — the
    explicit path has no microbatch scan. ``grad_clip_norm`` also
    applies ONLY to the explicit dispatch (the step clips by a
    psum-reconstructed global norm before its sharded update); on the
    implicit path clipping rides the optax chain (train/optim.py), and
    this argument is ignored.

    ``accum_steps > 1`` splits the global batch into that many
    microbatches and accumulates their mean gradient in a ``lax.scan``
    before the single optimizer update, at 1/A the activation memory.
    Exactly the full-batch gradient for uniformly-weighted losses
    (tested); for masked losses (MLM) each microbatch normalizes by its
    own mask count, so the result is the mean of per-microbatch means —
    a slight reweighting when mask counts differ. The microbatch dim
    must divide the batch; metrics are microbatch means; stat
    collections keep the last microbatch's values, like the last slice
    of one big batch would.

    ``grad_norm_metric``: report the pre-clip global gradient norm as
    ``metrics["grad_norm"]`` — one fused reduction over leaves XLA
    already has in registers, the standard divergence/LR-tuning
    signal. Off by default to keep metric dicts stable for parity
    tests.

    ``skip_nonfinite`` (resilience.nonfinite=skip_batch): when the
    step's loss or gradient norm is non-finite, the update is
    discarded ON DEVICE — params, optimizer state, stat collections,
    and EMA all keep their pre-step values (the select happens before
    ``tx.update``'s outputs are committed, so Adam moments are never
    poisoned), and only the step counter advances. The bad batch is
    simply dropped from the optimization trajectory. Host-side budget
    enforcement lives in resilience.policies, reading the (still
    non-finite) reported loss; ``metrics["skipped_nonfinite"]``
    reports 1.0 on a skipped step. The select is replicated-by-
    construction (loss and grad norm are global reductions), so every
    device takes the same branch — multi-host safe.

    ``health_every`` (observe.health): every that-many steps the step
    computes per-top-level-module training vitals — grad norm,
    update-to-param ratio, param RMS (observe/health.py) — ON DEVICE,
    gated by a ``lax.cond`` on the traced step counter so off-cadence
    steps pay neither the norm reductions nor any extra transfer (the
    scalars ride the existing metrics pytree; ``health_emit`` flags
    the real fetches). 0 = off (metric dict unchanged).
    """

    if grad_sync != "implicit":
        if accum_steps != 1:
            raise ValueError(
                f"grad_sync={grad_sync!r} has no microbatch scan; "
                f"accum_steps must be 1, got {accum_steps}")
        if state_template is None:
            raise ValueError(
                f"grad_sync={grad_sync!r} needs state_template (the "
                f"state the loop threads — it pins the opt-slot "
                f"shardings the sharded update runs against)")
        from tensorflow_distributed_tpu.parallel import overlap
        return overlap.make_explicit_train_step(
            mesh, state_template, seed=seed, loss=loss,
            batch_shardings=batch_shardings, grad_sync=grad_sync,
            bucket_bytes=(grad_sync_bucket_bytes
                          or overlap.DEFAULT_BUCKET_BYTES),
            fsdp_min_size=grad_sync_min_size or FSDP_MIN_SIZE,
            donate=donate, grad_norm_metric=grad_norm_metric,
            ema_decay=ema_decay,
            params_out_shardings=params_out_shardings,
            skip_nonfinite=skip_nonfinite, health_every=health_every,
            grad_clip_norm=grad_clip_norm, jit=jit)

    if batch_shardings is None:
        batch_shardings = default_batch_shardings(mesh)

    def grads_of(state, batch, dkey):
        grad_fn = jax.value_and_grad(
            partial(loss, state.apply_fn), has_aux=True)
        (_, (metrics, new_extra)), grads = grad_fn(
            state.params, state.extra, batch, dkey, True)
        # Activation-RMS taps (sown "health" collection) become
        # metrics HERE so the accum scan's carry keeps state.extra's
        # structure.
        metrics, new_extra = _pop_taps(metrics, new_extra)
        return grads, metrics, new_extra

    def step(state: TrainState, batch: Batch) -> Tuple[TrainState, Metrics]:
        # Per-step dropout key derived on-device from the step counter —
        # no host round-trip, fully deterministic (utils.prng).
        dkey = prng.step_key(seed, state.step)
        if accum_steps == 1:
            grads, metrics, new_extra = grads_of(state, batch, dkey)
        else:
            def to_micro(x, sharding):
                m = x.reshape(accum_steps, x.shape[0] // accum_steps,
                              *x.shape[1:])
                # Pin the (shifted) batch-dim sharding so the layout
                # stays defined. A batch-sized permute per step remains
                # (contiguous microbatches straddle the per-device
                # blocks) — negligible next to activations, but a
                # shard-local split would eliminate it if profiling
                # ever says otherwise.
                spec = jax.sharding.PartitionSpec(None, *sharding.spec)
                return jax.lax.with_sharding_constraint(
                    m, jax.sharding.NamedSharding(mesh, spec))

            micro = jax.tree_util.tree_map(to_micro, batch,
                                           batch_shardings)

            # lax.scan accumulating the mean gradient.
            zero_grads = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)

            def body(carry, mb):
                # The extra slot in the carry only TRANSPORTS the most
                # recent microbatch's stat collections out of the scan
                # in O(1) memory — it is never fed back in: each
                # microbatch recomputes from the closed-over
                # state.extra, so the final value is the last
                # microbatch's, like the last slice of one big batch.
                acc_grads, _last_extra, i = carry
                mkey = jax.random.fold_in(dkey, i)
                g, metrics, new_extra = grads_of(state, mb, mkey)
                acc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32) / accum_steps,
                    acc_grads, g)
                return (acc, new_extra, i + 1), metrics

            (grads, new_extra, _), metrics_stack = jax.lax.scan(
                body, (zero_grads, state.extra, jnp.zeros((), jnp.int32)),
                micro)
            metrics = jax.tree_util.tree_map(
                lambda m: jnp.mean(m, axis=0), metrics_stack)
        if grad_norm_metric:
            metrics = dict(metrics, grad_norm=optax.global_norm(grads))
        ok = None
        if skip_nonfinite:
            # Loss catches forward-side NaNs, the grad norm catches
            # backward-only ones (finite loss, overflowed grads).
            ok = (jnp.isfinite(metrics["loss"])
                  & jnp.isfinite(optax.global_norm(grads)))
            metrics = dict(metrics,
                           skipped_nonfinite=jnp.where(ok, 0.0, 1.0))
        updates, new_opt = state.tx.update(grads, state.opt_state, state.params)
        if health_every:
            # Per-module vitals, computed inside a lax.cond on the
            # cadence flag (observe/health.py): off-cadence steps pay
            # a few zeros. gate() also zeroes the activation taps
            # between cadences so every health/ scalar shares one
            # validity flag.
            metrics = dict(metrics, **observe_health.stats(
                state.params, grads, updates, state.step, health_every))
            metrics = observe_health.gate(
                metrics, metrics[observe_health.EMIT_KEY] > 0)
        new_params = jax.tree_util.tree_map(
            lambda p, u: (p + u.astype(p.dtype)), state.params, updates)
        if params_out_shardings is not None:
            # ZeRO-1's defining invariant: each device computed its
            # SLICE of the update (the slots are data-sharded), and
            # this constraint is the allgather that restores the
            # params' own layout — a tree of the params'
            # state-creation shardings, so legitimately-sharded params
            # (TP "model" annotations, pipe-stacked blocks) keep those
            # axes instead of being force-replicated. Without it GSPMD
            # propagates the slot sharding into new_params and every
            # later step pays FSDP-style per-use gathers the zero1
            # mode exists to avoid.
            new_params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_params,
                params_out_shardings)
        if ok is not None:
            # Discard the whole update on a non-finite step: the NaN
            # sits in the not-taken where branch, so nothing poisoned
            # survives (params, slots, stats). Selected BEFORE the EMA
            # update so the average tracks only applied params.
            def keep_old(new, old):
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o), new, old)

            new_params = keep_old(new_params, state.params)
            new_opt = keep_old(new_opt, state.opt_state)
            new_extra = keep_old(new_extra, state.extra)
        new_ema = state.ema
        if ema_decay and state.ema is not None:
            new_ema = ema_update(state.ema, new_params, ema_decay,
                                 state.step)
            if ok is not None:
                # A skipped step must not perturb the average either
                # (the update toward unchanged params still moves the
                # EMA and its bias correction).
                new_ema = keep_old(new_ema, state.ema)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt, extra=new_extra,
                                  ema=new_ema)
        return new_state, metrics

    if not jit:
        # Raw step body — for callers that embed it in a larger jitted
        # program (train.multistep's scan).
        return step
    with mesh:
        return observe_device.instrument_jit(
            "train_step", step,
            in_shardings=(None, batch_shardings),
            donate_argnums=(0,) if donate else (),
        )


def make_eval_step(mesh: Mesh, loss: LossFn = loss_fn,
                   batch_shardings: Any = None
                   ) -> Callable[[TrainState, Batch], Metrics]:
    """Jitted eval: loss + metrics over a (sharded) eval batch — the
    reference's validation pass (mnist_python_m.py:309-320) as one SPMD
    call instead of 5 feed_dict sess.runs."""
    if batch_shardings is None:
        batch_shardings = default_batch_shardings(mesh)

    def step(state: TrainState, batch: Batch) -> Metrics:
        # Polyak preference: evaluate the EMA weights when tracked
        # (None-ness is pytree structure — a trace-time branch).
        params = state.params if state.ema is None else state.ema
        _, (metrics, _) = loss(state.apply_fn, params, state.extra,
                               batch, jax.random.key(0), False)
        return metrics

    with mesh:
        return observe_device.instrument_jit(
            "eval_step", step,
            in_shardings=(None, batch_shardings),
            out_shardings=replicated(mesh),
        )
