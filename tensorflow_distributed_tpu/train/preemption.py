"""Preemption-aware stopping: SIGTERM -> durable checkpoint -> exit 0.

The reference's fault story was reactive: a worker died, the Supervisor
restarted it and restored from the last periodic checkpoint
(mnist_python_m.py:245-253), losing everything since. Preemptible TPU
VMs hand out an eviction NOTICE (SIGTERM) before the kill — acting on
it converts "lose up to checkpoint_every steps" into "lose nothing":
the loop stops at a safe step, takes one final durable checkpoint, and
exits cleanly for the scheduler to restart with ``--resume``.

Stopping must be COORDINATED under multi-host SPMD: if process 0
breaks at step i while process 1 dispatches step i+1, process 1's
collectives wait forever for a partner. Two regimes:

- Multi-process: ride JAX's preemption sync manager
  (``multihost_utils.reached_preemption_sync_point``) — the
  coordination service propagates any host's SIGTERM to all hosts and
  agrees on the first safe step; every process returns True at the
  SAME step. Our own signal flag is deliberately ignored here.
- Single process: a plain signal-handler flag (there is nobody to
  coordinate with).
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Optional

import jax

from tensorflow_distributed_tpu.observe import goodput as _goodput


class PreemptionGuard:
    """Decides, once per step, whether to stop for a preemption notice.

    Usage (what train.loop does)::

        guard = PreemptionGuard()
        for i in ...:
            if guard.should_stop(i):
                break            # falls through to the final save
        guard.close()

    ``close()`` restores the previous signal handlers (important under
    pytest, where the default handler must come back).
    """

    def __init__(self, enabled: bool = True,
                 signals: tuple = (signal.SIGTERM,)):
        self._flag = threading.Event()
        self._prev: dict = {}
        self._enabled = enabled
        self.fired: Optional[int] = None  # step at which we stopped
        self._notice_time: Optional[float] = None  # SIGTERM arrival
        if not enabled:
            return
        if jax.process_count() > 1:
            # Multi-host: the coordination service's own notifier
            # (installed by jax.distributed.initialize) must keep the
            # process-level SIGTERM disposition — installing a Python
            # handler here would clobber it and the sync manager would
            # never learn of the preemption. should_stop consults the
            # sync point instead.
            return
        for sig in signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                # Not the main thread (library embedded in a server):
                # signal handlers can't be installed; degrade to
                # cadence checkpoints.
                pass

    def _on_signal(self, signum, frame):
        if self._notice_time is None:
            self._notice_time = time.perf_counter()
            # Snapshot overhead charged so far — charged() includes the
            # elapsed part of an in-flight eval/checkpoint block (the
            # handler runs on the main thread, same thread as the
            # block) — so the drain charge in should_stop can exclude
            # exactly the overhead accrued INSIDE the notice window.
            counter = _goodput.get_active()
            self._notice_overhead = (counter.charged()
                                     if counter else 0.0)
        self._flag.set()

    def should_stop(self, step_id: int) -> bool:
        """True when THIS step is the coordinated safe stopping point.

        Call with consecutive step ids — the multi-host protocol
        computes max-over-hosts + 1 as the safe step and needs to see
        every step from every host.
        """
        if not self._enabled:
            # No checkpoint dir to save into: stopping early would
            # discard work and exit 0 as if complete.
            return False
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            try:
                stop = multihost_utils.reached_preemption_sync_point(
                    step_id)
            except RuntimeError:
                # Sync manager not initialized (preemption service
                # disabled): refusing to stop is the safe behavior —
                # an uncoordinated per-process stop can hang the other
                # processes' collectives. Cadence checkpoints remain.
                return False
            if stop:
                self.fired = step_id
            return stop
        if self._flag.is_set():
            self.fired = step_id
            if self._notice_time is not None:
                # Goodput: the notice->coordinated-safe-step interval is
                # preemption DRAIN time (the eviction grace window spent
                # finishing in-flight steps, not making new progress) —
                # minus whatever eval/checkpoint overhead was already
                # charged inside that same interval.
                drain = time.perf_counter() - self._notice_time
                counter = _goodput.get_active()
                if counter is not None:
                    drain -= (counter.charged()
                              - getattr(self, "_notice_overhead", 0.0))
                _goodput.add("drain", max(drain, 0.0))
                self._notice_time = None
            return True
        return False

    def close(self) -> None:
        """Restore the previous signal dispositions; idempotent, and
        safe to call whether or not a notice ever arrived or
        ``should_stop`` ever consumed it. Also drops any un-consumed
        notice state so a closed guard can never charge drain time to
        a goodput counter installed by a LATER run."""
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()
        self._notice_time = None
        self._flag.clear()
