"""The 1F1B pipelined train step.

The standard train step (train.step) gets pipeline parallelism "for
free" by differentiating through ``pipeline_apply`` — GPipe semantics:
all forwards, then AD replays all backwards, so every stage stashes
O(M) microbatch residuals. This module is the 1F1B alternative: it
does NOT call jax.grad over the pipeline at all. Gradients come from
``parallel.pipeline.pipeline_value_and_grad``, which schedules
backward microbatches into the same scan as the forwards (the loss is
computed at the last stage inside the schedule), bounding per-stage
activation state to an input stash of depth min(2S, M) — independent
of the microbatch count.

What remains under ordinary AD is only the embedding (outside the
pipe): its gradient is assembled from the d_x the scheduled backward
emits at stage 0, via one jax.vjp around the embed call. Head (final
LN + lm_head) gradients come out of the schedule's last stage. The two
shell contributions add: grads_shell = d(embed path) + d(head path).

Loss parity: per-microbatch CE pieces are UNNORMALIZED sums seeded
with cotangent_scale = 1/total_mask, so accumulated gradients and the
reported loss equal the mean-masked-CE of the whole global batch
exactly — the same objective mlm_loss computes (train.tasks), which is
what makes the 1F1B-vs-GPipe parity test exact rather than approximate.

No reference counterpart: the reference has no pipeline parallelism at
all (SURVEY.md §2b checklist) — both schedules are beyond-reference,
TPU-native designs.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from tensorflow_distributed_tpu.models.pipelined import PipelinedLM
from tensorflow_distributed_tpu.observe import device as observe_device
from tensorflow_distributed_tpu.observe import health as observe_health
from tensorflow_distributed_tpu.ops.losses import masked_ce_sums
from tensorflow_distributed_tpu.parallel.pipeline import (
    interleaved_pipeline_value_and_grad, pipeline_value_and_grad)
from tensorflow_distributed_tpu.train.state import TrainState, ema_update
from tensorflow_distributed_tpu.train.tasks import (
    MOE_AUX_WEIGHT, mlm_batch_shardings)
from tensorflow_distributed_tpu.utils import prng


def make_1f1b_train_step(model: PipelinedLM, mesh: Mesh, seed: int = 0,
                         batch_shardings: Any = None, donate: bool = True,
                         jit: bool = True,
                         moe_aux_weight: float = MOE_AUX_WEIGHT,
                         moe_zloss_weight: float = 0.0,
                         grad_norm_metric: bool = False,
                         label_smoothing: float = 0.0,
                         ema_decay: float = 0.0,
                         backward: str = "recompute",
                         ce_chunk: int = 0,
                         params_out_shardings: Any = None,
                         health_every: int = 0
                         ) -> Callable[[TrainState, Any],
                                       Tuple[TrainState, Dict]]:
    """Build the jitted 1F1B step for a PipelinedLM.

    Consumes the same {tokens, targets, mask} batches, TrainState, and
    optimizer as the standard step — only the schedule differs. When
    the model is MoE (cfg.moe_experts > 0), the router losses sown
    inside the pipeline are collected through the schedule and seeded
    as extra vjp cotangents, so the objective matches the non-pipelined
    MoE loss: CE + moe_aux_weight * load_balance
    + moe_zloss_weight * z_loss (train.tasks.make_moe_loss).

    ``backward`` forwards to pipeline_value_and_grad: "recompute"
    (input stash + per-stage remat — minimal memory) or "stash"
    (residual stash, no forward recompute — the higher-MFU trade; see
    that function's docstring and PARITY.md for the chip numbers).

    ``ce_chunk`` > 0 fuses the head into the per-microbatch loss
    (ops/fused_ce.py, scan formulation): last_fn hands the schedule's
    head vjp the chunked custom-VJP op instead of dense logits, so the
    last stage never materializes [mb, L, V] — it composes because the
    schedule already drives last_fn through an explicit jax.vjp.

    ``params_out_shardings`` (ZeRO-1, param_partition="zero1"): the
    params' state-creation sharding tree, constrained onto new_params
    after the optimizer apply. The update itself happens OUTSIDE the
    pipe shard_map under plain GSPMD, so data-sharded Adam slots
    compose with the schedule untouched: each device updates its slot
    slice, and this constraint is the allgather that restores the
    pipe(/TP)-only param layout — without it the slot sharding
    propagates into the params and the next step's pipe shard_map
    pays per-use data-axis gathers (see train.step's twin note).

    ``health_every`` (observe.health): cadence-gated per-top-module
    vitals like the standard step's — here the modules are "shell"
    (embedding + head) and "blocks" (the [S, ...] stage stack), the
    partition the pipelined param tree actually has. Activation taps
    are not available (the stage fn runs inside a manual shard_map).
    """
    if batch_shardings is None:
        batch_shardings = mlm_batch_shardings(mesh)
    use_dropout = bool(model.cfg.dropout_rate)
    moe = model.cfg.moe_experts > 0
    V = getattr(model, "virtual_stages", 1)
    if V > 1 and backward != "recompute":
        # Mirrors config.validate's rejection — the interleaved
        # schedule implements the recompute backward only (see
        # interleaved_pipeline_value_and_grad).
        raise ValueError("pipeline_backward='stash' is not supported "
                         "with virtual stages; use 'recompute'")
    # mesh.seq > 1 routes the stage through ring attention, whose
    # seq-ppermutes cannot live inside the cond-skipped bubble
    # branches (collectives under per-pipe-rank control flow — see
    # pipeline_value_and_grad's ``bubble`` note): fall back to
    # where-masked predication for those meshes.
    from tensorflow_distributed_tpu.parallel.mesh import AXIS_SEQ
    bubble = "where" if mesh.shape[AXIS_SEQ] > 1 else "cond"

    def _sched(*args, **kw):
        if V > 1:
            kw.pop("backward", None)
            return interleaved_pipeline_value_and_grad(
                *args, virtual_stages=V, **kw)
        return pipeline_value_and_grad(*args, **kw)

    def step(state: TrainState, batch: Any) -> Tuple[TrainState, Dict]:
        tokens, targets = batch["tokens"], batch["targets"]
        mask = batch["mask"]
        total = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
        shell, blocks = state.params["shell"], state.params["blocks"]
        dkey = prng.step_key(seed, state.step)

        x, embed_vjp = jax.vjp(lambda sp: model.embed(sp, tokens), shell)

        stage_fn = model.make_stage_fn(train=True, with_rng=use_dropout,
                                       with_aux=moe)

        if ce_chunk:
            from tensorflow_distributed_tpu.ops.fused_ce import (
                fused_ce_sums)

            def last_fn(sp, y_mb, aux_mb):
                feats, w, bias, v_axis = model.head_pieces(sp, y_mb)
                tgt, msk = aux_mb
                ce_sum, correct, n = fused_ce_sums(
                    feats, w, bias, tgt, msk, w.shape[v_axis], ce_chunk,
                    label_smoothing, v_axis)
                return ce_sum, {"correct": correct, "mask": n}
        else:
            def last_fn(sp, y_mb, aux_mb):
                logits = model.head(sp, y_mb)
                tgt, msk = aux_mb
                ce_sum, correct, n = masked_ce_sums(logits, tgt, msk,
                                                    label_smoothing)
                return ce_sum, {"correct": correct, "mask": n}

        kw = dict(rng=dkey if use_dropout else None,
                  cotangent_scale=1.0 / total, backward=backward,
                  bubble=bubble)
        aux_metrics = {}
        if moe:
            # Each (layer, microbatch) sow contributes 1/denom to the
            # mean the objective weights — the cotangent seed per stage
            # call is therefore weight/denom.
            denom = model.cfg.n_layers * model.num_microbatches
            aux_cot = {"load_balance": moe_aux_weight / denom,
                       "z_loss": moe_zloss_weight / denom,
                       "dropped_fraction": 0.0}
            ce_sum, sums, aux_sums, (d_blocks, d_shell_head, d_x) = (
                _sched(
                    stage_fn, last_fn, blocks, shell, x,
                    (targets, mask), mesh, model.num_microbatches,
                    stage_aux_cotangent=aux_cot, **kw))
            aux_metrics = {"aux_loss": aux_sums["load_balance"] / denom,
                           "z_loss": aux_sums["z_loss"] / denom,
                           "dropped_frac":
                               aux_sums["dropped_fraction"] / denom}
        else:
            ce_sum, sums, (d_blocks, d_shell_head, d_x) = (
                _sched(
                    stage_fn, last_fn, blocks, shell, x,
                    (targets, mask), mesh, model.num_microbatches, **kw))
        (d_shell_embed,) = embed_vjp(d_x.astype(x.dtype))
        d_shell = jax.tree_util.tree_map(
            lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32),
            d_shell_embed, d_shell_head)
        grads = {"shell": d_shell, "blocks": d_blocks}

        updates, new_opt = state.tx.update(grads, state.opt_state,
                                           state.params)
        health = (observe_health.stats(state.params, grads, updates,
                                       state.step, health_every)
                  if health_every else {})
        new_params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype), state.params, updates)
        if params_out_shardings is not None:
            new_params = jax.tree_util.tree_map(
                jax.lax.with_sharding_constraint, new_params,
                params_out_shardings)
        metrics = {"loss": ce_sum / total,
                   "accuracy": sums["correct"] / jnp.maximum(
                       sums["mask"], 1.0), **aux_metrics, **health}
        if grad_norm_metric:
            metrics["grad_norm"] = optax.global_norm(grads)
        new_ema = state.ema
        if ema_decay and state.ema is not None:
            new_ema = ema_update(state.ema, new_params, ema_decay,
                                 state.step)
        new_state = state.replace(step=state.step + 1, params=new_params,
                                  opt_state=new_opt, ema=new_ema)
        return new_state, metrics

    if not jit:
        step.observe_hw_recompute = (backward == "recompute")
        return step
    with mesh:
        jitted = observe_device.instrument_jit(
            "pipelined_train_step", step,
            in_shardings=(None, batch_shardings),
            donate_argnums=(0,) if donate else (),
        )
    # Observability metadata: the recompute backward EXECUTES ~4x-forward
    # for the block stack while model-FLOPs accounting credits 3x;
    # observe.hub reads this to report hw_mfu alongside model MFU
    # (observe.mfu.pipelined_hw_flops_per_token). The instrument wrapper
    # is a plain function, so the attribute rides it like it rode the
    # PjitFunction.
    jitted.observe_hw_recompute = (backward == "recompute")
    return jitted
