"""Train state: the entire mutable world of a run, as one pytree.

Replaces the reference's scattered mutable state — ps-resident Variables
(mnist_python_m.py:185-196), Adam slots, the ``global_step`` Variable
(:178), and accumulator/queue state inside SyncReplicasOptimizer — with
one immutable pytree threaded through a jitted step. ``step`` increments
once per aggregated update exactly like the reference's global_step
(SURVEY.md N15); there is no separate local_step because SPMD has no
stale gradients to count.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import optax
from flax import struct
from jax.sharding import Mesh

from tensorflow_distributed_tpu.parallel.sharding import param_sharding, replicated
from tensorflow_distributed_tpu.utils import prng


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any

    # Static (non-pytree) fields:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)


def create_train_state(model: nn.Module, tx: optax.GradientTransformation,
                       sample_input: jax.Array, mesh: Mesh, seed: int = 0
                       ) -> TrainState:
    """Initialize params/opt-state and place them on the mesh.

    Every process calls this with the same seed and gets bit-identical
    params — replacing the reference's chief-initializes-then-others-wait
    protocol (``prepare_or_wait_for_session``, mnist_python_m.py:264-275).
    Partition-annotated params land sharded; everything else replicated.
    """
    # Abstract init to read partition metadata without allocating.
    abstract = jax.eval_shape(
        lambda k: model.init(k, sample_input, train=False),
        jax.random.key(0))
    # param_sharding maps each metadata box (or bare leaf) to a
    # NamedSharding, yielding a tree with the *unboxed* structure.
    shardings = param_sharding(mesh, abstract["params"])

    def init_params(key):
        v = model.init(key, sample_input, train=False)
        return nn.meta.unbox(v["params"])

    with mesh:
        params = jax.jit(init_params, out_shardings=shardings)(
            prng.init_key(seed))
        # Adam's m/v mirror the params elementwise, so jit propagates the
        # param shardings into the optimizer state.
        opt_state = jax.jit(tx.init)(params)
        step = jax.device_put(jax.numpy.zeros((), jax.numpy.int32),
                              replicated(mesh))
    return TrainState(step=step, params=params, opt_state=opt_state,
                      apply_fn=model.apply, tx=tx)


def param_count(params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
