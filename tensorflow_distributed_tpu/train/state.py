"""Train state: the entire mutable world of a run, as one pytree.

Replaces the reference's scattered mutable state — ps-resident Variables
(mnist_python_m.py:185-196), Adam slots, the ``global_step`` Variable
(:178), and accumulator/queue state inside SyncReplicasOptimizer — with
one immutable pytree threaded through a jitted step. ``step`` increments
once per aggregated update exactly like the reference's global_step
(SURVEY.md N15); there is no separate local_step because SPMD has no
stale gradients to count.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import optax
from flax import struct
from jax.sharding import Mesh

from tensorflow_distributed_tpu.parallel.sharding import (
    FSDP_MIN_SIZE, param_sharding, path_key, replicated)
from tensorflow_distributed_tpu.utils import prng

# Collections sown per-forward-pass (diagnostics/aux losses), never
# persisted: carrying an init-time snapshot in TrainState.extra would
# re-feed it to apply() every step, where sow's tuple-append semantics
# would stack fresh values on the stale constant (biasing e.g. the MoE
# load-balance loss) and bloat every checkpoint.
TRANSIENT_COLLECTIONS = ("moe_aux", "intermediates", "health")


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any

    # Static (non-pytree) fields:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    # Non-param variable collections (e.g. BatchNorm "batch_stats").
    # Updated in the forward pass, not by the optimizer — the moving
    # averages ride along the state pytree and checkpoint with it.
    # Empty dict for stat-free models (CNN, transformer).
    extra: Any = struct.field(default_factory=dict)

    # Exponential moving average of params (None = disabled). Updated
    # by the train step after each optimizer apply; the eval step
    # prefers it over the raw params when present (Polyak averaging —
    # the eval-smoothness trick big-model trainers ship by default).
    # Checkpoints carry it like any other leaf.
    ema: Any = None


def create_train_state(model: nn.Module, tx: optax.GradientTransformation,
                       sample_input: jax.Array, mesh: Mesh, seed: int = 0,
                       fsdp: bool = False,
                       fsdp_min_size: int = FSDP_MIN_SIZE,
                       opt_fsdp: bool = False,
                       ema: bool = False) -> TrainState:
    """Initialize params/opt-state and place them on the mesh.

    Every process calls this with the same seed and gets bit-identical
    params — replacing the reference's chief-initializes-then-others-wait
    protocol (``prepare_or_wait_for_session``, mnist_python_m.py:264-275).
    Partition-annotated params land sharded; everything else replicated.

    ``fsdp=True`` (config ``param_partition="fsdp"``): large params —
    and, via the slot-matching below, their Adam m/v mirrors — shard
    one dim over the "data" axis (ZeRO-3; parallel.sharding). The
    train step is unchanged: GSPMD sees the same jit with different
    argument shardings and inserts the gather/scatter pair. Where the
    reference streamed FULL weights ps->worker every step over TCP
    (mnist_python_m.py:177, SURVEY.md N4), this streams each shard
    once per use over ICI and never materializes full optimizer state
    anywhere.

    ``opt_fsdp=True`` (config ``param_partition="zero1"``): ZeRO
    stage 1 — params stay replicated (no per-use gathers in the
    forward/backward) but the optimizer slots that mirror them shard
    over "data". Each device updates its slice of the moments and the
    param delta; GSPMD's one allgather on ``p + u`` re-replicates the
    params. Memory: optimizer state drops ~1/data, the usual best
    deal when params fit but Adam doubles don't.
    """
    (abstract, var_shardings, shardings, abstract_opt,
     opt_shardings) = derive_state_shardings(
        model, tx, sample_input, mesh, fsdp=fsdp,
        fsdp_min_size=fsdp_min_size, opt_fsdp=opt_fsdp)

    def init_vars(key):
        return nn.meta.unbox(model.init(key, sample_input, train=False))

    # Init OUTSIDE the mesh context: with a live mesh, flax's
    # DenseGeneral validates its multi-dim kernels by applying the
    # boxed rank-4 partition constraint to the pre-reshape rank-2
    # value — a rank mismatch that rejects any tp_partitioning init at
    # mesh.model > 1. The out_shardings are NamedShardings and carry
    # the mesh themselves, so placement is identical; only the
    # context-dependent constraint inside init is skipped.
    variables = jax.jit(init_vars, out_shardings=var_shardings)(
        prng.init_key(seed))
    with mesh:
        params = variables["params"]
        extra = {k: v for k, v in variables.items()
                 if k != "params" and k not in TRANSIENT_COLLECTIONS}
        opt_state = jax.jit(tx.init, out_shardings=opt_shardings)(params)
        step = jax.device_put(jax.numpy.zeros((), jax.numpy.int32),
                              replicated(mesh))
    ema_params = None
    if ema:
        with mesh:
            # Start at the init params, placed identically (sharded
            # leaves stay sharded — EMA costs 1/data per device under
            # FSDP like the params themselves).
            ema_params = jax.jit(
                lambda p: jax.tree_util.tree_map(jax.numpy.array, p),
                out_shardings=shardings)(params)
    return TrainState(step=step, params=params, opt_state=opt_state,
                      apply_fn=model.apply, tx=tx, extra=extra,
                      ema=ema_params)


def derive_state_shardings(model: nn.Module,
                           tx: optax.GradientTransformation,
                           sample_input: jax.Array, mesh: Mesh,
                           fsdp: bool = False,
                           fsdp_min_size: int = FSDP_MIN_SIZE,
                           opt_fsdp: bool = False):
    """The state-layout derivation, WITHOUT allocating anything.

    Returns ``(abstract_variables, var_shardings, param_shardings,
    abstract_opt_state, opt_shardings)`` — the abstract (eval_shape)
    variable/optimizer trees plus the NamedShardings
    :func:`create_train_state` places them with. Factored out so the
    auto-layout planner (:func:`abstract_train_state`) can score THE
    layout a run would actually get — FSDP/ZeRO-1 slot-matching rules
    included — from exactly one implementation.
    """
    # Abstract init to read partition metadata without allocating.
    abstract = jax.eval_shape(
        lambda k: model.init(k, sample_input, train=False),
        jax.random.key(0))
    # param_sharding maps each metadata box (or bare leaf) to a
    # NamedSharding, yielding a tree with the *unboxed* structure.
    # Applied to the full variables dict it also covers non-param
    # collections (batch_stats, ...), which are bare -> replicated.
    var_shardings = param_sharding(mesh, abstract)
    if fsdp:
        # FSDP is scoped to the params subtree: non-param collections
        # (batch_stats, ...) stay replicated — they are read every
        # forward pass and small, so sharding them buys nothing.
        var_shardings = {
            **var_shardings,
            "params": param_sharding(mesh, abstract["params"], fsdp=True,
                                     fsdp_min_size=fsdp_min_size)}
    shardings = var_shardings["params"]

    # Optimizer-state shardings: slots that mirror a param tensor (Adam
    # m/v, momentum) get that param's sharding; scalars (step counts)
    # are replicated. Left to jit's choosing they end up committed to
    # device 0, which breaks mesh-wide reuse after checkpoint restore.
    # Matching is by key path: optax slot trees embed copies of the
    # param tree, so an opt leaf at (...,'0','mu','conv1','kernel')
    # matches the param path ('conv1','kernel') as a suffix. (Shape-
    # keyed matching would collide for same-shape params partitioned
    # differently, e.g. TP in- vs out-projections.)
    abstract_params = nn.meta.unbox(abstract["params"])
    param_shapes = {
        path_key(path): leaf.shape
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            abstract_params)[0]}
    param_path_to_sharding = {
        path_key(path): sd
        for path, sd in jax.tree_util.tree_flatten_with_path(shardings)[0]}
    if opt_fsdp and not fsdp:
        # ZeRO-1: slots shard the way the params WOULD under FSDP,
        # while the params themselves stay replicated.
        slot_tree = param_sharding(mesh, abstract["params"], fsdp=True,
                                   fsdp_min_size=fsdp_min_size)
        slot_path_to_sharding = {
            path_key(path): sd
            for path, sd in jax.tree_util.tree_flatten_with_path(
                slot_tree)[0]}
    else:
        slot_path_to_sharding = param_path_to_sharding

    def opt_leaf_sharding(path, leaf):
        keys = path_key(path)
        for i in range(len(keys)):
            if keys[i:] in slot_path_to_sharding:
                # Slots that don't MIRROR the param (adafactor's
                # factored v_row/v_col live at the param's path but
                # with reduced shape) can't inherit its sharding.
                if getattr(leaf, "shape", None) != param_shapes[keys[i:]]:
                    return replicated(mesh)
                return slot_path_to_sharding[keys[i:]]
        return replicated(mesh)

    abstract_opt = jax.eval_shape(tx.init, abstract_params)
    opt_shardings = jax.tree_util.tree_map_with_path(
        opt_leaf_sharding, abstract_opt)
    return abstract, var_shardings, shardings, abstract_opt, opt_shardings


def abstract_train_state(model: nn.Module,
                         tx: optax.GradientTransformation,
                         sample_input: jax.Array, mesh: Mesh,
                         fsdp: bool = False,
                         fsdp_min_size: int = FSDP_MIN_SIZE,
                         opt_fsdp: bool = False,
                         ema: bool = False) -> TrainState:
    """A :class:`TrainState` of sharding-annotated ShapeDtypeStructs —
    the EXACT layout :func:`create_train_state` would place (same
    derivation, :func:`derive_state_shardings`) without allocating a
    byte on any device.

    Enough to drive the AOT API: ``make_train_step(...).lower(state,
    batch).compile()`` accepts this state and yields the real
    program's ``cost_analysis``/``memory_analysis`` — what the
    auto-layout planner scores candidates with, including mesh shapes
    too big (or, on a skewed container, too broken) to ever
    materialize here.
    """
    (abstract, _, shardings, abstract_opt,
     opt_shardings) = derive_state_shardings(
        model, tx, sample_input, mesh, fsdp=fsdp,
        fsdp_min_size=fsdp_min_size, opt_fsdp=opt_fsdp)

    def _sds(leaf, sharding):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                    sharding=sharding)

    abstract_params = nn.meta.unbox(abstract["params"])
    params = jax.tree_util.tree_map(_sds, abstract_params, shardings)
    opt_state = jax.tree_util.tree_map(_sds, abstract_opt, opt_shardings)
    rep = replicated(mesh)
    extra = {
        k: jax.tree_util.tree_map(lambda a: _sds(a, rep), v)
        for k, v in nn.meta.unbox(abstract).items()
        if k != "params" and k not in TRANSIENT_COLLECTIONS}
    step = jax.ShapeDtypeStruct((), jax.numpy.int32, sharding=rep)
    ema_params = (jax.tree_util.tree_map(_sds, abstract_params,
                                         shardings) if ema else None)
    return TrainState(step=step, params=params, opt_state=opt_state,
                      apply_fn=model.apply, tx=tx, extra=extra,
                      ema=ema_params)


def ema_update(ema: Any, new_params: Any, decay: float,
               step: jax.Array) -> Any:
    """One Polyak step with the standard warmup debias: the effective
    decay is min(decay, (1+step)/(10+step)), so early steps track the
    params closely instead of averaging in the random init — without
    this, decay=0.999 over a 1000-step run leaves the init weights
    with ~0.37 of the final average and eval reports near-random
    metrics while the raw params are fine. The ONE implementation,
    shared by the standard and 1F1B step builders.
    """
    step = step.astype(jax.numpy.float32)
    d = jax.numpy.minimum(decay, (1.0 + step) / (10.0 + step))
    return jax.tree_util.tree_map(
        lambda e, p: d * e + (1.0 - d) * p.astype(e.dtype),
        ema, new_params)


def param_count(params: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
