"""tensorflow_distributed_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities of
PranjalSahu/tensorflow_distributed (a TF-1.x parameter-server MNIST
trainer, see /root/reference):

- The reference's ps/worker/gRPC topology (``tf.train.Server``,
  ``replica_device_setter``, ``SyncReplicasOptimizer`` —
  mnist_python_m.py:146-233) is replaced by a single jit-compiled SPMD
  train step over a ``jax.sharding.Mesh``: gradient synchronization is an
  XLA ``psum`` allreduce over ICI, not a push/pull through a parameter
  server over TCP.
- The single-device path (mnist_single.py) and the distributed path are
  the *same* train step on meshes of different shapes — no per-role
  script copies, no chief/non-chief init dance.

Package layout:
    config          one config surface replacing the 14 tf.app.flags
    parallel/       mesh construction, sharding rules, collectives,
                    sequence-parallel ring attention
    models/         CNN (reference parity), ResNet, Transformer/BERT
    ops/            losses/metrics + Pallas TPU kernels
    data/           MNIST idx loader, synthetic data, sharded batching
    train/          train state, jitted steps, loop, checkpointing
    utils/          prng, logging, timing
    native/         C++ data-plane helpers (idx parse, batch assembly)
"""

__version__ = "0.1.0"

# Fill jax API-skew gaps (jax.shard_map / get_abstract_mesh on older
# containers) before any module touches them; no-op on current jax.
# Tolerate a missing jax entirely: the graftcheck lint tier
# (analysis/lint.py, pure stdlib by contract) and the config surface
# must import — and run — on boxes that never installed an accelerator
# stack. Anything that actually computes still fails loudly at ITS
# import, with the real ModuleNotFoundError.
try:
    from tensorflow_distributed_tpu.utils import jaxcompat as _jaxcompat
except ModuleNotFoundError as _e:
    if _e.name not in ("jax", "jaxlib"):
        # Only an absent accelerator stack is survivable here — any
        # other missing module is a real packaging error that must
        # surface NOW, not as a skipped shim's AttributeError later.
        raise
    _jaxcompat = None  # no jax: lint/config-only environment
else:
    _jaxcompat.install()

from tensorflow_distributed_tpu.config import TrainConfig  # noqa: F401,E402
