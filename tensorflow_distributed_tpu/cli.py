"""Single CLI entrypoint — ``python -m tensorflow_distributed_tpu.cli``.

Replaces all five reference entrypoints (mnist_python_m.py / _w1 / _w2 /
mnist_single.py / the notebook) and their ``tf.app.run`` dispatch
(mnist_python_m.py:323-324). Role selection by editing per-file flag
defaults is gone: every process runs this same module; mesh shape and
env-driven bootstrap decide the topology.

Examples:
    # single device (the mnist_single.py path):
    python -m tensorflow_distributed_tpu.cli --train-steps 200

    # 8-way data parallel on one host:
    python -m tensorflow_distributed_tpu.cli --mesh.data 8

    # reference-faithful hyperparameters (for apples-to-apples runs):
    python -m tensorflow_distributed_tpu.cli --init-scheme reference \
        --learning-rate 0.01 --log-every 1

    # continuous-batching inference (serve/; README "Serving"):
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --serve.num-slots 8 --serve.num-requests 32

    # fast-path serving (README "Fast-path serving"): speculative
    # decoding (k-gram self-draft; token-identical by construction),
    # int8 KV cache (~2x slots per HBM at head dim 64), SLO classes
    # with per-tenant quotas + preempt-and-requeue
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --serve.num-slots 4 --serve.num-requests 32 \
        --serve.spec-tokens 4 --serve.kv-dtype int8 \
        --serve.policy slo --serve.slo-mix "high:0.25,batch:0.25" \
        --serve.tenants 4 --serve.tenant-quota 512

    # tensor-parallel serving (README "Tensor-parallel serving"): the
    # replica itself sharded over a model=2 mesh — params AND every
    # slot-cache leaf head-sharded, per-device cache bytes / 2,
    # token-identical to the single-device engine; composes with the
    # spec/int8/paged flags above ("--family serve" on the planner
    # ranks the widths without executing)
    # (odd vocabs like GPT-2's 50257 need --shard-vocab true to pad)
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --model-size tiny --serve.mesh-model 2 \
        --serve.num-slots 8 --serve.num-requests 32

    # paged KV + radix prefix reuse (serve/paging; README "Paged KV
    # + prefix reuse"): shared system prompts / few-shot headers /
    # multi-turn sessions attach cached pages instead of
    # re-prefilling, and slots hold pages for their actual
    # trajectory instead of reserving max_len rows
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --serve.num-slots 8 --serve.num-requests 32 \
        --serve.paged true --serve.page-size 16 \
        --serve.session-turns 2

    # serve under fire (README "Serving under faults"): bursty
    # arrivals, slot-NaN containment + live weight swap drills, a
    # crash-durable request journal, decode watchdog; run under
    # resilience.supervisor for SIGKILL coverage
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --checkpoint-dir /tmp/ckpt \
        --serve.trace bursty --serve.arrival-rate 8 \
        --serve.journal /tmp/serve.journal \
        --resilience.sync-timeout-s 60 \
        --resilience.fault-plan "slot_nan@6:1,reload@10,sigkill@14"

    # serve observatory (observe/serve_trace.py + observe/slo.py;
    # README "Serve tracing & SLO monitoring"): per-request Perfetto
    # trace (open at https://ui.perfetto.dev), live SLO burn-rate
    # monitor with slo_alert/slo_ok events + a periodic status line,
    # and atomic rolling-metrics snapshots a router can poll
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --serve.num-slots 4 --serve.num-requests 32 \
        --serve.policy slo --serve.slo-mix "high:0.25" \
        --observe.metrics-jsonl serve.jsonl \
        --observe.trace serve.trace.json \
        --observe.slo "high:ttft_p95=100ms,tok_p50=30ms" \
        --observe.export-every 1 --observe.export-path serve.snap.json

    # autopilot (observe/autopilot.py; README "Autopilot"): the online
    # controller closing the calibrate→plan→act loop on the run's own
    # telemetry — SLO burn drives admission, page-pool pressure the
    # live slot cap, the rolling accept rate the speculation depth,
    # and plan drift a calibration refit; every decision is an
    # auditable `tune` record, every actuation token-identical (pin
    # knobs it must not touch with --observe.autopilot-pin)
    python -m tensorflow_distributed_tpu.cli --mode serve \
        --model gpt_lm --serve.num-slots 4 --serve.num-requests 64 \
        --serve.spec-tokens 4 --serve.policy slo \
        --observe.autopilot true --observe.autopilot-every 25 \
        --observe.autopilot-pin buckets \
        --observe.autopilot-calibration serve.calibration.json \
        --observe.metrics-jsonl serve.jsonl \
        --observe.slo "ttft_p95=250ms"

    # fleet serving (fleet/; README "Fleet serving"): a health-aware
    # router + lifecycle controller over N replica processes — each
    # an ordinary --mode serve command with a per-epoch inbox/journal/
    # snapshot workspace; replicas die, restart, hot-swap trainer
    # checkpoints (rolling, one at a time) while the fleet keeps
    # answering with zero lost requests
    python -m tensorflow_distributed_tpu.fleet.run \
        --replicas 3 --fleet-dir /tmp/fleet \
        --requests workload.jsonl --checkpoint-dir /tmp/ckpt \
        --kill r1@12.5 --hold-export r0@20:3 \
        -- --mode serve --model gpt_lm --seq-len 96 \
           --checkpoint-dir /tmp/ckpt --serve.num-slots 4 \
           --observe.anomaly true

    # fleet observatory (observe/fleet_trace.py + fleetview; README
    # "Fleet observatory"): one stitched Perfetto trace across router
    # + every replica (failover legs land on one timeline), fleet-level
    # SLO burn on client-perceived latency across retries, per-request
    # latency decomposition, and an atomically-rewritten control-plane
    # snapshot the fleetview CLI renders as a one-screen status page
    python -m tensorflow_distributed_tpu.fleet.run \
        --replicas 2 --fleet-dir /tmp/fleet \
        --requests workload.jsonl \
        --fleet.trace true \
        --fleet.slo "ttft_p95=200ms,tok_p99=80ms" \
        --fleet.export-path /tmp/fleet/fleet_snapshot.json \
        --fleet.export-every 1 \
        -- --mode serve --model gpt_lm --serve.num-slots 4
    python -m tensorflow_distributed_tpu.observe.fleetview /tmp/fleet

    # graftcheck runtime checks (analysis/runtime.py; README "Static
    # analysis"): transfer guard + sharding-contract assertion
    python -m tensorflow_distributed_tpu.cli --train-steps 100 --check true

    # elastic restarts (README "Elastic restarts"): supervise with
    # --elastic and a chip-loss drill — the restart probes the
    # surviving devices, degrades the mesh, and the resharded restore
    # continues training instead of crash-looping
    python -m tensorflow_distributed_tpu.resilience.supervisor \
        --elastic -- --mesh.data 8 --checkpoint-dir /tmp/ckpt \
        --checkpoint-every 50 \
        --resilience.fault-plan "device_loss@120:4"

    # device telemetry (observe/device.py + observe/health.py; README
    # "Device telemetry"): compiled-program cost/HBM records + per-layer
    # health vitals in the metrics JSONL
    python -m tensorflow_distributed_tpu.cli --model gpt_lm \
        --model-size tiny --observe.metrics-jsonl /tmp/m.jsonl \
        --observe.health true --observe.health-taps true

    # auto-layout planner (analysis/planner; README "Auto-layout
    # planner"): rank every valid mesh x strategy by AOT cost model,
    # or let the train CLI launch with the winner (--plan auto emits
    # an auditable "plan" record through observe)
    python -m tensorflow_distributed_tpu.analysis.planner \
        --family gpt --devices 8 --batch-size 128
    python -m tensorflow_distributed_tpu.cli --model gpt_lm \
        --model-size tiny --plan auto \
        --observe.metrics-jsonl /tmp/m.jsonl

    # overlap-aware gradient sync (parallel/overlap.py; README
    # "Gradient-sync overlap"): bucketed reduce-scatter + ZeRO-1
    # sharded update + bucketed all-gather, hidden under backward
    # compute; step records carry the exposed-vs-hidden comm estimate
    python -m tensorflow_distributed_tpu.cli --model gpt_lm \
        --mesh.data 8 --param-partition zero1 --grad-sync overlap \
        --grad-sync-bucket-mb 4 \
        --observe.metrics-jsonl /tmp/m.jsonl

    # ground-truth observatory (observe/xprof.py + planner/calibrate;
    # README "Ground-truth observatory"): the profiler window is
    # parsed into per-program device_time records beside the compile
    # records, --plan auto scores on measured effective rates, and a
    # plan_drift record closes predicted -> measured at run end
    python -m tensorflow_distributed_tpu.cli --model gpt_lm \
        --model-size tiny --plan auto \
        --plan-calibration calibration.json \
        --profile-dir /tmp/prof --observe.metrics-jsonl /tmp/m.jsonl
    # did a rerun regress any committed bench gate?
    python -m tensorflow_distributed_tpu.observe.regress

    # incident observatory (observe/anomaly.py + observe/flightrec.py;
    # README "Incident observatory"): online anomaly detection over
    # the already-fetched log-cadence values + a crash flight
    # recorder whose bundle survives even a SIGKILL — render it as a
    # human incident report with the postmortem CLI
    python -m tensorflow_distributed_tpu.cli --model mnist_cnn \\
        --dataset synthetic --train-steps 200 --log-every 1 \\
        --observe.metrics-jsonl m.jsonl --observe.anomaly true \\
        --observe.flightrec /tmp/flight \\
        --resilience.nonfinite skip_batch \\
        --resilience.fault-plan "nan_grad@60,sigkill@120"
    python -m tensorflow_distributed_tpu.observe.postmortem \\
        /tmp/flight/flight-<pid>.jsonl
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from tensorflow_distributed_tpu.config import parse_args
from tensorflow_distributed_tpu.parallel.mesh import is_chief
from tensorflow_distributed_tpu.resilience.watchdog import StallError
from tensorflow_distributed_tpu.train.loop import (
    evaluate_only, generate_only, train)
from tensorflow_distributed_tpu.utils.compilecache import (
    enable_persistent_cache)

# Distinct exit codes for the failure classes a supervisor (e.g.
# resilience.supervisor) or scheduler wants to tell apart in logs:
# 2 = diverged (train: non-finite halt / recovery budget exhausted;
# serve: a request slot-quarantined past its retry budget — either
# way a restart re-diverges), 3 = stall watchdog fired (train data/
# sync or serve decode — a restart is exactly the remedy; serve legs
# resume from the request journal). Clean completion and graceful
# preemption both exit 0.
EXIT_DIVERGED = 2
EXIT_STALLED = 3


def main(argv: Optional[Sequence[str]] = None) -> int:
    enable_persistent_cache()
    cfg = parse_args(argv)
    if cfg.mode == "eval":
        evaluate_only(cfg)
        return 0
    if cfg.mode == "generate":
        generate_only(cfg)
        return 0
    if cfg.mode == "serve":
        # Continuous-batching inference over a request workload
        # (serve/run.py): slots join/leave one hot compiled decode
        # step, prompts prefill through a bounded bucket ladder.
        # Same exit-code contract as training, serve-shaped: a
        # request slot-quarantined past its retry budget is serve's
        # divergence (2 — deterministic decode would re-poison; the
        # supervisor must NOT hot-loop restarts), a decode watchdog
        # breach is a stall (3 — a restart + journal resume is
        # exactly the remedy).
        from tensorflow_distributed_tpu.serve.run import serve_run
        from tensorflow_distributed_tpu.serve.scheduler import (
            SlotRetryExhausted)
        try:
            serve_run(cfg)
        except SlotRetryExhausted as e:
            print(f"[resilience] serve diverged: {e}", file=sys.stderr,
                  flush=True)
            return EXIT_DIVERGED
        except StallError as e:
            print(f"[resilience] serve stalled: {e}", file=sys.stderr,
                  flush=True)
            return EXIT_STALLED
        return 0
    try:
        result = train(cfg)
    except FloatingPointError as e:
        print(f"[resilience] diverged: {e}", file=sys.stderr, flush=True)
        return EXIT_DIVERGED
    except StallError as e:
        print(f"[resilience] stalled: {e}", file=sys.stderr, flush=True)
        return EXIT_STALLED
    if is_chief():
        # Emit the reference's hand-maintained `performance` table
        # automatically (performance:1-6).
        table = result.logger.performance_table(cfg.learning_rate)
        if table.count("\n"):
            print(table)
        # Compiled-program HBM budget table (observe/device.py) —
        # printed when the run registered programs (a sink was
        # configured and --observe.programs wasn't turned off).
        from tensorflow_distributed_tpu.observe import (
            device as observe_device)
        budget = observe_device.budget_table()
        if budget and cfg.observe.programs:
            print(budget)
        # Point at the observe/ artifacts this run produced.
        if cfg.observe.metrics_jsonl:
            print(f"[observe] metrics: {cfg.observe.metrics_jsonl} "
                  f"(summarize: python -m "
                  f"tensorflow_distributed_tpu.observe.report "
                  f"{cfg.observe.metrics_jsonl})")
        if cfg.observe.trace:
            print(f"[observe] host trace: {cfg.observe.trace} "
                  f"(open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
