#!/usr/bin/env bash
# Tier-1 wrapper: the ROADMAP.md verify command plus ONE automatic rerun
# when the suite dies to the known container XLA:CPU SIGSEGV/heap-abort
# (the jax runtime intermittently corrupts the allocator under
# concurrent dispatch + host transfers; reproduced on the untouched
# seed tree). A genuine test failure still prints a pytest summary line
# and is NOT retried — the abort is detected specifically via a MISSING
# summary line, so tier-1 numbers stop being flake-gated without ever
# masking a real red.
#
# Usage: scripts/t1.sh          (from the repo root)
#   T1_LOG=/path/override.log scripts/t1.sh
set -o pipefail

LOG="${T1_LOG:-/tmp/_t1.log}"
cd "$(dirname "$0")/.."

# graftcheck first (scripts/lint.sh: AST lint + jaxpr census vs
# goldens): cheap, deterministic, and a finding there is actionable
# without reading 400s of pytest output. The suite still runs either
# way so tier-1 numbers keep flowing; a lint red is carried into the
# final exit code below.
lint_rc=0
scripts/lint.sh || lint_rc=$?

run_suite() {
  rm -f "$LOG"
  timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
  return "${PIPESTATUS[0]}"
}

has_summary_line() {
  # pytest's final tally ("34 failed, 303 passed, ... in 493.83s" —
  # bare under -q, ===-decorated otherwise, "no tests ran" when
  # collection found nothing); a runtime abort kills the process
  # before it prints.
  grep -qaE '([0-9]+ (passed|failed|errors?)|no tests ran)' "$LOG"
}

run_suite
rc=$?
if ! has_summary_line; then
  echo "[t1] no pytest summary line in $LOG (known container XLA:CPU" \
       "abort) — rerunning once" >&2
  run_suite
  rc=$?
fi

echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"

# Firebench smoke (serve-under-fire: slot-NaN containment, live weight
# swap, SIGKILL + journal resume — benchmarks/firebench.py): tiny
# config, 2 slots, CPU. The smoke gates CORRECTNESS (zero lost
# requests, 100% token identity, every drill actually fired) plus a
# 0.3 sanity floor on goodput — at smoke scale (~0.3 s of serving)
# the injected stall alone dominates the wall, so the real >= 0.8
# goodput gate lives in the committed FIREBENCH.json run, not here.
# Same abort-guard shape as the pytest rerun: a run that dies to the
# known container XLA:CPU abort prints no fire_checks line and is
# retried once; a genuine gate failure prints one and is NOT retried.
FIRELOG="${FIRELOG:-/tmp/_t1_fire.log}"
run_firebench() {
  rm -f "$FIRELOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.firebench \
    --requests 12 --new-tokens 32 --seq-len 48 --stall-s 0.2 \
    --min-goodput 0.3 --out "" 2>&1 | tee "$FIRELOG"
  return "${PIPESTATUS[0]}"
}
run_firebench
fire_rc=$?
if ! grep -qa '"metric": "fire_checks"' "$FIRELOG"; then
  echo "[t1] no fire_checks line in $FIRELOG (known container" \
       "XLA:CPU abort) — rerunning firebench once" >&2
  run_firebench
  fire_rc=$?
fi
if [ "$fire_rc" -ne 0 ]; then
  echo "[t1] firebench smoke FAILED (fire_rc=$fire_rc) — see" \
       "$FIRELOG" >&2
fi

# Elasticbench smoke (elastic restarts: device_loss -> supervisor
# --elastic shrinks mesh 2 -> 1 -> resharded resume continues —
# benchmarks/elasticbench.py): tiny CPU run, CORRECTNESS-gated (loss
# identical to the uninterrupted baseline within 1e-3, zero completed
# steps lost, the reshard actually happened); the committed
# ELASTICBENCH.json run carries the full 4->2 and 4->8 matrix. Same
# abort-guard shape as the smokes above: a run that dies to the known
# container XLA:CPU abort prints no elastic_checks line and is
# retried once; a genuine gate failure prints one and is NOT retried.
ELASTICLOG="${ELASTICLOG:-/tmp/_t1_elastic.log}"
run_elasticbench() {
  rm -f "$ELASTICLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.elasticbench \
    --devices 2 --lose 1 --grow-to 0 --steps 8 --ckpt-every 2 \
    --out "" 2>&1 | tee "$ELASTICLOG"
  return "${PIPESTATUS[0]}"
}
run_elasticbench
elastic_rc=$?
if ! grep -qa '"metric": "elastic_checks"' "$ELASTICLOG"; then
  echo "[t1] no elastic_checks line in $ELASTICLOG (known container" \
       "XLA:CPU abort) — rerunning elasticbench once" >&2
  run_elasticbench
  elastic_rc=$?
fi
if [ "$elastic_rc" -ne 0 ]; then
  echo "[t1] elasticbench smoke FAILED (elastic_rc=$elastic_rc) —" \
       "see $ELASTICLOG" >&2
fi

# Planbench smoke (auto-layout planner pick quality: enumerate ->
# AOT-score -> actually execute the tiny-gpt sweep and require the
# planner's top pick within 15% of the best measured candidate, with
# the predicted peak-HBM ordering matching the executed compiles —
# benchmarks/planbench.py): tiny config, gpt only, CPU. The committed
# PLANBENCH.json run carries the full gpt+moe sweep. Same abort-guard
# shape as the smokes above: a run that dies to the known container
# XLA:CPU abort prints no plan_checks line and is retried once; a
# genuine gate failure prints one and is NOT retried.
PLANLOG="${PLANLOG:-/tmp/_t1_plan.log}"
run_planbench() {
  rm -f "$PLANLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.planbench \
    --families gpt --steps 6 --batch 32 --out "" 2>&1 | tee "$PLANLOG"
  return "${PIPESTATUS[0]}"
}
run_planbench
plan_rc=$?
if ! grep -qa '"metric": "plan_checks"' "$PLANLOG"; then
  echo "[t1] no plan_checks line in $PLANLOG (known container" \
       "XLA:CPU abort) — rerunning planbench once" >&2
  run_planbench
  plan_rc=$?
fi
if [ "$plan_rc" -ne 0 ]; then
  echo "[t1] planbench smoke FAILED (plan_rc=$plan_rc) — see" \
       "$PLANLOG" >&2
fi

# Gradsync smoke (overlap-aware grad sync: serial psum vs bucketed
# reduce-scatter/all-gather on the real tiny-gpt step, mesh 2 —
# benchmarks/gradsync.py --family gpt): identity-gated (serial and
# overlap training bit-equal incl. a skipped NaN step) plus the
# step-time gate at the CPU tolerance; the committed GRADSYNC.json
# run carries the mesh-4 A/B. Same abort-guard shape as the smokes
# above: a run that dies to the known container XLA:CPU abort prints
# no gradsync_checks line and is retried once; a genuine gate failure
# prints one and is NOT retried.
GRADSYNCLOG="${GRADSYNCLOG:-/tmp/_t1_gradsync.log}"
run_gradsync() {
  rm -f "$GRADSYNCLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.gradsync \
    --family gpt --devices 2 --steps 6 --batch 16 --seq-len 32 \
    --out "" 2>&1 | tee "$GRADSYNCLOG"
  return "${PIPESTATUS[0]}"
}
run_gradsync
gradsync_rc=$?
if ! grep -qa '"metric": "gradsync_checks"' "$GRADSYNCLOG"; then
  echo "[t1] no gradsync_checks line in $GRADSYNCLOG (known container" \
       "XLA:CPU abort) — rerunning gradsync once" >&2
  run_gradsync
  gradsync_rc=$?
fi
if [ "$gradsync_rc" -ne 0 ]; then
  echo "[t1] gradsync smoke FAILED (gradsync_rc=$gradsync_rc) — see" \
       "$GRADSYNCLOG" >&2
fi

# Servebench smoke (fast-path serving: speculative decoding on the
# memorized bigram-cycle model, int8 KV slots-at-budget + divergence,
# SLO-vs-FIFO p95 TTFT under a burst — benchmarks/servebench.py).
# Skips the base continuous-vs-sequential phase (pinned in
# tests/test_serve.py and the committed SERVEBENCH.json); gates one
# SPECULATIVE and one INT8 config token-identity + thresholds, and
# asserts the artifact's new p95_ttft_under_load / accept_rate fields
# exist. Same abort-guard shape as the smokes above: a run that dies
# to the known container XLA:CPU abort prints no serve_checks line
# and is retried once; a genuine gate failure prints one and is NOT
# retried.
SERVELOG="${SERVELOG:-/tmp/_t1_serve.log}"
run_servebench() {
  rm -f "$SERVELOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.servebench \
    --phases spec,int8,slo --requests 8 --slo-requests 16 \
    --spec-new-tokens 48 --out "" 2>&1 | tee "$SERVELOG"
  return "${PIPESTATUS[0]}"
}
run_servebench
serve_rc=$?
if ! grep -qa '"metric": "serve_checks"' "$SERVELOG"; then
  echo "[t1] no serve_checks line in $SERVELOG (known container" \
       "XLA:CPU abort) — rerunning servebench once" >&2
  run_servebench
  serve_rc=$?
fi
if [ "$serve_rc" -eq 0 ]; then
  # The fields the SLO/spec artifact is consumed by (README, observe
  # report): their absence is a regression even when gates pass.
  if ! grep -qa '"p95_ttft_under_load"' "$SERVELOG" \
      || ! grep -qa '"accept_rate"' "$SERVELOG"; then
    echo "[t1] servebench output is missing p95_ttft_under_load /" \
         "accept_rate fields" >&2
    serve_rc=1
  fi
fi
if [ "$serve_rc" -ne 0 ]; then
  echo "[t1] servebench smoke FAILED (serve_rc=$serve_rc) — see" \
       "$SERVELOG" >&2
fi

# Servebench TP smoke (tensor-parallel replica: --serve.mesh-model 2
# over the 8 virtual CPU devices — benchmarks/servebench.py --phases
# tp). Separate run from the spec/int8/slo smoke above so its timing
# envelope is untouched. Gates are pure CORRECTNESS plus the cache
# arithmetic: per-device cache bytes/slot ratio >= 1.9 (exact head
# sharding gives 2.0) and token identity of the model=2 engine vs the
# model=1 engine across dense, int8-KV and speculative configs. The
# per-step collective schedule itself is pinned by the
# serve_decode_tp/serve_verify_tp census goldens in scripts/lint.sh.
# Same abort-guard shape as the smokes above: a run that dies to the
# known container XLA:CPU abort prints no serve_checks line and is
# retried once; a genuine gate failure prints one and is NOT retried.
TPLOG="${TPLOG:-/tmp/_t1_serve_tp.log}"
run_servebench_tp() {
  rm -f "$TPLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.servebench \
    --phases tp --requests 6 --new-tokens 16 --out "" \
    2>&1 | tee "$TPLOG"
  return "${PIPESTATUS[0]}"
}
run_servebench_tp
tp_rc=$?
if ! grep -qa '"metric": "serve_checks"' "$TPLOG"; then
  echo "[t1] no serve_checks line in $TPLOG (known container" \
       "XLA:CPU abort) — rerunning servebench tp once" >&2
  run_servebench_tp
  tp_rc=$?
fi
if [ "$tp_rc" -ne 0 ]; then
  echo "[t1] servebench tp smoke FAILED (tp_rc=$tp_rc) — see" \
       "$TPLOG" >&2
fi

# Slobench smoke (serve observatory: per-request trace validity +
# span balance across a SIGKILL restart, burn-rate alert fires on the
# over-capacity burst and stays quiet on the clean control, snapshot
# agrees with the report — benchmarks/slobench.py). Tiny scale; the
# overhead A/B gate lives in the committed SLOBENCH.json run, not
# here (subprocess timing at smoke scale is noise). Same abort-guard
# shape as the smokes above: a run that dies to the known container
# XLA:CPU abort prints no slo_checks line and is retried once; a
# genuine gate failure prints one and is NOT retried.
SLOLOG="${SLOLOG:-/tmp/_t1_slo.log}"
run_slobench() {
  rm -f "$SLOLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.slobench \
    --requests 10 --new-tokens 32 --seq-len 48 --stall-s 0.15 \
    --slo "ttft_p95=100ms" --slo-windows "16,64" --skip-overhead \
    --out "" 2>&1 | tee "$SLOLOG"
  return "${PIPESTATUS[0]}"
}
run_slobench
slo_rc=$?
if ! grep -qa '"metric": "slo_checks"' "$SLOLOG"; then
  echo "[t1] no slo_checks line in $SLOLOG (known container" \
       "XLA:CPU abort) — rerunning slobench once" >&2
  run_slobench
  slo_rc=$?
fi
if [ "$slo_rc" -ne 0 ]; then
  echo "[t1] slobench smoke FAILED (slo_rc=$slo_rc) — see" \
       "$SLOLOG" >&2
fi

# Detectbench smoke (incident observatory: anomaly recall on the
# standard train+serve fault plans, zero false positives on the
# seeded clean runs, SIGKILL flight-recorder bundle named in the
# supervisor's restart event + postmortem CLI renders it —
# benchmarks/detectbench.py). Skips the overhead phase (subprocess
# timing at smoke scale is noise; the committed DETECTBENCH.json run
# carries it). Same abort-guard shape as the smokes above: a run that
# dies to the known container XLA:CPU abort prints no detect_checks
# line and is retried once; a genuine gate failure prints one and is
# NOT retried.
DETECTLOG="${DETECTLOG:-/tmp/_t1_detect.log}"
run_detectbench() {
  rm -f "$DETECTLOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.detectbench \
    --phases train,serve,bundle --train-steps 24 --serve-requests 8 \
    --new-tokens 32 --out "" 2>&1 | tee "$DETECTLOG"
  return "${PIPESTATUS[0]}"
}
run_detectbench
detect_rc=$?
if ! grep -qa '"metric": "detect_checks"' "$DETECTLOG"; then
  echo "[t1] no detect_checks line in $DETECTLOG (known container" \
       "XLA:CPU abort) — rerunning detectbench once" >&2
  run_detectbench
  detect_rc=$?
fi
if [ "$detect_rc" -ne 0 ]; then
  echo "[t1] detectbench smoke FAILED (detect_rc=$detect_rc) — see" \
       "$DETECTLOG" >&2
fi

# Pagebench smoke (paged KV + radix prefix reuse: dense-vs-paged
# token identity on a shared-prefix + session trace, prefill tokens
# saved, slots-at-budget, warm-TTFT — benchmarks/pagebench.py). Tiny
# scale with relaxed FLOPs/TTFT floors (fewer requests = fewer warm
# hits; subprocess timing at smoke scale is noisy) — the committed
# PAGEBENCH.json run carries the real >= 0.6 saved / 1.5x slots /
# 0.9 TTFT gates. Identity and lost=0 stay exact. Same abort-guard
# shape as the smokes above: a run that dies to the known container
# XLA:CPU abort prints no page_checks line and is retried once; a
# genuine gate failure prints one and is NOT retried.
PAGELOG="${PAGELOG:-/tmp/_t1_page.log}"
run_pagebench() {
  rm -f "$PAGELOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.pagebench \
    --requests 6 --prefix-len 32 --new-tokens 6 --turn2-gap 0.05 \
    --min-flops-saved 0.35 --min-slots-ratio 1.2 \
    --max-warm-ttft-ratio 1.5 --out "" 2>&1 | tee "$PAGELOG"
  return "${PIPESTATUS[0]}"
}
run_pagebench
page_rc=$?
if ! grep -qa '"metric": "page_checks"' "$PAGELOG"; then
  echo "[t1] no page_checks line in $PAGELOG (known container" \
       "XLA:CPU abort) — rerunning pagebench once" >&2
  run_pagebench
  page_rc=$?
fi
if [ "$page_rc" -ne 0 ]; then
  echo "[t1] pagebench smoke FAILED (page_rc=$page_rc) — see" \
       "$PAGELOG" >&2
fi

# Fleetbench smoke (fleet serving: health-aware router + failover
# re-dispatch over 2 REAL replicas — benchmarks/fleetbench.py,
# identity phase only): one replica SIGKILLED mid-stream, gates are
# pure CORRECTNESS — zero lost requests, every assembled stream
# token-identical to the single-replica reference, death/restart/
# redispatch drills proven fired. The train->serve loop phase
# (goodput, rolling swaps, staleness) lives in the committed
# FLEETBENCH.json run, not here. Same abort-guard shape: a run that
# dies to the known container XLA:CPU abort prints no fleet_checks
# line and is retried once; a genuine gate failure prints one and is
# NOT retried.
FLEETLOG="${FLEETLOG:-/tmp/_t1_fleet.log}"
run_fleetbench() {
  rm -f "$FLEETLOG"
  timeout -k 10 420 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.fleetbench \
    --phases identity --identity-requests 10 --new-tokens 16 \
    --seq-len 48 --out "" 2>&1 | tee "$FLEETLOG"
  return "${PIPESTATUS[0]}"
}
run_fleetbench
fleet_rc=$?
if ! grep -qa '"metric": "fleet_checks"' "$FLEETLOG"; then
  echo "[t1] no fleet_checks line in $FLEETLOG (known container" \
       "XLA:CPU abort) — rerunning fleetbench once" >&2
  run_fleetbench
  fleet_rc=$?
fi
if [ "$fleet_rc" -ne 0 ]; then
  echo "[t1] fleetbench smoke FAILED (fleet_rc=$fleet_rc) — see" \
       "$FLEETLOG" >&2
fi

# Fleet-observatory smoke (cross-replica trace stitching + e2e SLO
# accounting — benchmarks/fleetobsbench.py, failover phase only): a
# 2-replica observed fleet with one SIGKILL + a survivor decode
# stall; gates are pure correctness — merged trace balanced with all
# three failover legs present, fleet SLO alert on fault / quiet on
# control, latency decomposition sums to e2e, exported snapshot ==
# report. The overhead phase (interleaved on/off throughput ratio)
# lives in the committed FLEETOBSBENCH.json run, not here. Same
# abort-guard shape as the benches above.
FLEETOBSLOG="${FLEETOBSLOG:-/tmp/_t1_fleetobs.log}"
run_fleetobsbench() {
  rm -f "$FLEETOBSLOG"
  timeout -k 10 420 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.fleetobsbench \
    --phases failover --requests 10 --new-tokens 32 --stall-s 3 \
    --slo "ttft_p95=30s,tok_p99=60ms" --residual-tol 0.25 \
    --out "" 2>&1 | tee "$FLEETOBSLOG"
  return "${PIPESTATUS[0]}"
}
run_fleetobsbench
fleetobs_rc=$?
if ! grep -qa '"metric": "fleetobs_checks"' "$FLEETOBSLOG"; then
  echo "[t1] no fleetobs_checks line in $FLEETOBSLOG (known" \
       "container XLA:CPU abort) — rerunning fleetobsbench once" >&2
  run_fleetobsbench
  fleetobs_rc=$?
fi
if [ "$fleetobs_rc" -ne 0 ]; then
  echo "[t1] fleetobsbench smoke FAILED (fleetobs_rc=$fleetobs_rc)" \
       "— see $FLEETOBSLOG" >&2
fi

# Tunebench smoke (autopilot: wrong-knob serve converges back toward
# the hand-tuned goodput under a shifting trace, a correctly-tuned
# control run stays at zero knob changes, the speculation loop deepens
# k on a perfect-accept draft, and token streams stay identical across
# every live actuation — benchmarks/tunebench.py). Correctness phases
# only: the CLI subprocess phase and the overhead A/B gate live in the
# committed TUNEBENCH.json run, not here (subprocess spawn + timing at
# smoke scale is noise). The convergence bar is loosened to 0.6 for
# the same reason — the hand-tuned denominator swings ~2x with host
# timing at this scale, while the wrong-knob run sits at ~0.3-0.5, so
# 0.6 still separates converged from not; the committed TUNEBENCH.json
# pins the real >=0.9 gate. Same abort-guard shape as the smokes above.
TUNELOG="${TUNELOG:-/tmp/_t1_tune.log}"
run_tunebench() {
  rm -f "$TUNELOG"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m \
    tensorflow_distributed_tpu.benchmarks.tunebench \
    --phases goodput,control,spec --min-goodput-ratio 0.6 \
    --out "" 2>&1 | tee "$TUNELOG"
  return "${PIPESTATUS[0]}"
}
run_tunebench
tune_rc=$?
if ! grep -qa '"metric": "tune_checks"' "$TUNELOG"; then
  echo "[t1] no tune_checks line in $TUNELOG (known container" \
       "XLA:CPU abort) — rerunning tunebench once" >&2
  run_tunebench
  tune_rc=$?
fi
if [ "$tune_rc" -ne 0 ]; then
  echo "[t1] tunebench smoke FAILED (tune_rc=$tune_rc) — see" \
       "$TUNELOG" >&2
fi

# Regress smoke (cross-run regression ledger — observe/regress.py):
# every committed artifact in the manifest compared against its own
# HEAD baseline; an untouched tree must pass CLEAN, and any slide in
# a committed gate (goodput, token identity, pick quality, ...) fails
# here before a human eyeballs a JSON diff. Pure stdlib/jax-free, so
# no XLA abort-guard rerun is needed; the summary-line check below
# still catches a silently-dead interpreter.
REGRESSLOG="${REGRESSLOG:-/tmp/_t1_regress.log}"
rm -f "$REGRESSLOG"
timeout -k 10 120 python -m tensorflow_distributed_tpu.observe.regress \
  2>&1 | tee "$REGRESSLOG"
regress_rc="${PIPESTATUS[0]}"
if ! grep -qa 'regress: .* checks' "$REGRESSLOG"; then
  echo "[t1] no regress summary line in $REGRESSLOG — rerunning once" >&2
  rm -f "$REGRESSLOG"
  timeout -k 10 120 python -m \
    tensorflow_distributed_tpu.observe.regress 2>&1 | tee "$REGRESSLOG"
  regress_rc="${PIPESTATUS[0]}"
fi
if [ "$regress_rc" -ne 0 ]; then
  echo "[t1] regress smoke FAILED (regress_rc=$regress_rc) — see" \
       "$REGRESSLOG" >&2
fi

if [ "$rc" -eq 0 ] && [ "$lint_rc" -ne 0 ]; then
  echo "[t1] suite green but graftcheck red (lint_rc=$lint_rc) — see" \
       "scripts/lint.sh output above" >&2
  exit "$lint_rc"
fi
if [ "$rc" -eq 0 ] && [ "$fire_rc" -ne 0 ]; then
  exit "$fire_rc"
fi
if [ "$rc" -eq 0 ] && [ "$elastic_rc" -ne 0 ]; then
  exit "$elastic_rc"
fi
if [ "$rc" -eq 0 ] && [ "$plan_rc" -ne 0 ]; then
  exit "$plan_rc"
fi
if [ "$rc" -eq 0 ] && [ "$gradsync_rc" -ne 0 ]; then
  exit "$gradsync_rc"
fi
if [ "$rc" -eq 0 ] && [ "$serve_rc" -ne 0 ]; then
  exit "$serve_rc"
fi
if [ "$rc" -eq 0 ] && [ "$tp_rc" -ne 0 ]; then
  exit "$tp_rc"
fi
if [ "$rc" -eq 0 ] && [ "$slo_rc" -ne 0 ]; then
  exit "$slo_rc"
fi
if [ "$rc" -eq 0 ] && [ "$detect_rc" -ne 0 ]; then
  exit "$detect_rc"
fi
if [ "$rc" -eq 0 ] && [ "$page_rc" -ne 0 ]; then
  exit "$page_rc"
fi
if [ "$rc" -eq 0 ] && [ "$fleet_rc" -ne 0 ]; then
  exit "$fleet_rc"
fi
if [ "$rc" -eq 0 ] && [ "$fleetobs_rc" -ne 0 ]; then
  exit "$fleetobs_rc"
fi
if [ "$rc" -eq 0 ] && [ "$tune_rc" -ne 0 ]; then
  exit "$tune_rc"
fi
if [ "$rc" -eq 0 ] && [ "$regress_rc" -ne 0 ]; then
  exit "$regress_rc"
fi
exit "$rc"
