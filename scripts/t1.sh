#!/usr/bin/env bash
# Tier-1 wrapper: the ROADMAP.md verify command plus ONE automatic rerun
# when the suite dies to the known container XLA:CPU SIGSEGV/heap-abort
# (the jax runtime intermittently corrupts the allocator under
# concurrent dispatch + host transfers; reproduced on the untouched
# seed tree). A genuine test failure still prints a pytest summary line
# and is NOT retried — the abort is detected specifically via a MISSING
# summary line, so tier-1 numbers stop being flake-gated without ever
# masking a real red.
#
# Usage: scripts/t1.sh          (from the repo root)
#   T1_LOG=/path/override.log scripts/t1.sh
set -o pipefail

LOG="${T1_LOG:-/tmp/_t1.log}"
cd "$(dirname "$0")/.."

# graftcheck first (scripts/lint.sh: AST lint + jaxpr census vs
# goldens): cheap, deterministic, and a finding there is actionable
# without reading 400s of pytest output. The suite still runs either
# way so tier-1 numbers keep flowing; a lint red is carried into the
# final exit code below.
lint_rc=0
scripts/lint.sh || lint_rc=$?

run_suite() {
  rm -f "$LOG"
  timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$LOG"
  return "${PIPESTATUS[0]}"
}

has_summary_line() {
  # pytest's final tally ("34 failed, 303 passed, ... in 493.83s" —
  # bare under -q, ===-decorated otherwise, "no tests ran" when
  # collection found nothing); a runtime abort kills the process
  # before it prints.
  grep -qaE '([0-9]+ (passed|failed|errors?)|no tests ran)' "$LOG"
}

run_suite
rc=$?
if ! has_summary_line; then
  echo "[t1] no pytest summary line in $LOG (known container XLA:CPU" \
       "abort) — rerunning once" >&2
  run_suite
  rc=$?
fi

echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
if [ "$rc" -eq 0 ] && [ "$lint_rc" -ne 0 ]; then
  echo "[t1] suite green but graftcheck red (lint_rc=$lint_rc) — see" \
       "scripts/lint.sh output above" >&2
  exit "$lint_rc"
fi
exit "$rc"
