#!/usr/bin/env bash
# graftcheck gate: the AST lint over the whole package (telemetry
# schema, durability, and argv-protocol contract rules included), the
# schema pass's RECORDS.md drift gate, then the jaxpr collective/upcast
# census against the committed goldens. Nonzero exit on any finding or
# drift. Invoked from scripts/t1.sh ahead of the pytest tier (fast: the
# lint and schema passes are pure stdlib, the census only traces — no
# XLA compiles).
#
# Usage: scripts/lint.sh            (from anywhere)
#
# On a red:
#   - lint finding: fix it, or suppress the statement with
#     '# graftcheck: disable=<rule> -- <reason>' (rule catalog:
#     python -m tensorflow_distributed_tpu.analysis.lint --list-rules)
#   - RECORDS.md drift: edit observe/schemas.py, then regenerate:
#     python -m tensorflow_distributed_tpu.analysis.schema --update
#   - census drift: if the collective/upcast change is intentional,
#     regenerate and commit the goldens:
#     python -m tensorflow_distributed_tpu.analysis.jaxprcheck --update
set -o pipefail
cd "$(dirname "$0")/.."

rc=0

python -m tensorflow_distributed_tpu.analysis.lint \
  tensorflow_distributed_tpu/ || rc=$?

# Schema pass: the telemetry-contract rule subset plus the RECORDS.md
# drift gate (the lint above already ran the rules repo-wide; this adds
# the generated-doc check and gives the contract its own CLI surface).
python -m tensorflow_distributed_tpu.analysis.schema || rc=$?

env JAX_PLATFORMS=cpu python -m tensorflow_distributed_tpu.analysis.jaxprcheck \
  || rc=$?

exit "$rc"
