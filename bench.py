"""Headline benchmark: MNIST-CNN training throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference's only recorded numbers (`performance:2-6`,
mirrored in BASELINE.md) give ~0.205 global steps/s at 256 images per
sync step => ~52 images/s AGGREGATE across its whole 1-ps + 2-worker
cluster. We report per-chip throughput here and still compare against
that aggregate figure, which is conservative in our favor on any
multi-chip run and exactly apples-to-oranges-free on one chip.
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_AGG_IMAGES_PER_SEC = 52.0  # BASELINE.md "derived throughput"


def main() -> None:
    import jax
    import optax

    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)
    enable_persistent_cache()

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.mnist import synthetic_mnist
    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.parallel.sharding import shard_batch
    from tensorflow_distributed_tpu.train.state import create_train_state
    from tensorflow_distributed_tpu.train.step import make_train_step

    from tensorflow_distributed_tpu.data.prefetch import prefetch_to_mesh
    from tensorflow_distributed_tpu.data.u8 import U8Dataset, U8ShardedBatcher

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev))
    global_batch = 256 * n_dev  # reference global batch per chip-pair scale
    train_ds, _, _ = synthetic_mnist(
        n_train=max(8 * global_batch, 8192), n_test=256,
        validation_size=256, seed=0)

    model = MnistCNN()  # bfloat16 compute — MXU-native
    state = create_train_state(
        model, optax.adam(1e-3), np.zeros((2, 28, 28, 1), np.float32), mesh)
    step = make_train_step(mesh)

    # End-to-end measurement: batches stream through the host data
    # pipeline (gather + device_put, double-buffered) exactly as in
    # training — not a device-resident compute-only loop. (The reference
    # likewise paid its feed_dict path every step.)
    batcher = U8ShardedBatcher(U8Dataset.from_float(train_ds),
                               global_batch, 0)
    it = prefetch_to_mesh(batcher.forever(), mesh, size=2)

    # Compile + warmup outside the timed window. Host readback, not
    # just block_until_ready — see the barrier note below.
    for _ in range(5):
        state, metrics = step(state, next(it))
    float(jax.device_get(metrics["loss"]))
    jax.block_until_ready(state.params)

    steps = 200
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, next(it))
    # Host readback, not just block_until_ready: on tunneled TPU
    # runtimes the latter can return before remote execution finishes,
    # inflating throughput; pulling a scalar that depends on the last
    # step is an honest barrier.
    float(jax.device_get(metrics["loss"]))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    images_per_sec = steps * global_batch / dt
    per_chip = images_per_sec / n_dev
    print(json.dumps({
        "metric": "mnist_cnn_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_AGG_IMAGES_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
