"""Headline benchmark: MNIST-CNN training throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference's only recorded numbers (`performance:2-6`,
mirrored in BASELINE.md) give ~0.205 global steps/s at 256 images per
sync step => ~52 images/s AGGREGATE across its whole 1-ps + 2-worker
cluster. We report per-chip throughput here and still compare against
that aggregate figure, which is conservative in our favor on any
multi-chip run and exactly apples-to-oranges-free on one chip.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

REFERENCE_AGG_IMAGES_PER_SEC = 52.0  # BASELINE.md "derived throughput"


def _init_platform() -> str:
    """Initialize the jax backend, falling back to CPU when the
    configured accelerator can't come up (e.g. the container's TPU
    plugin registered but the device is unavailable — BENCH_r05 died
    to exactly that ``Unable to initialize backend 'axon'``). A bench
    that crashes reports nothing; a CPU number TAGGED with its
    platform keeps the trajectory comparable. Raises only when even
    the CPU backend is unusable."""
    import jax

    try:
        jax.devices()
    except RuntimeError as e:
        print(f"[bench] accelerator backend unavailable "
              f"({str(e).splitlines()[0]}); retrying on CPU",
              file=sys.stderr, flush=True)
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass  # a backend initialized after all — use it
        jax.devices()  # CPU too broken -> raise: nothing to bench on
    return jax.default_backend()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--out", default="",
                        help="also write the record to this JSONL file "
                        "(observe.registry format; summarizable "
                        "artifacts, not scraped stdout)")
    args = parser.parse_args(argv)
    platform = _init_platform()
    import jax
    import optax

    from tensorflow_distributed_tpu.utils.compilecache import (
        enable_persistent_cache)
    enable_persistent_cache()

    from tensorflow_distributed_tpu.config import MeshConfig
    from tensorflow_distributed_tpu.data.mnist import synthetic_mnist
    from tensorflow_distributed_tpu.data.prefetch import prefetch_with
    from tensorflow_distributed_tpu.data.u8 import U8Dataset, U8ShardedBatcher
    from tensorflow_distributed_tpu.models.cnn import MnistCNN
    from tensorflow_distributed_tpu.parallel.mesh import make_mesh
    from tensorflow_distributed_tpu.train.multistep import (
        make_multi_step, stacked_batch_shardings)
    from tensorflow_distributed_tpu.train.state import create_train_state

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshConfig(data=n_dev))
    global_batch = 256 * n_dev  # reference global batch per chip-pair scale
    train_ds, _, _ = synthetic_mnist(
        n_train=max(8 * global_batch, 8192), n_test=256,
        validation_size=256, seed=0)

    model = MnistCNN()  # bfloat16 compute — MXU-native
    state = create_train_state(
        model, optax.adam(1e-3), np.zeros((2, 28, 28, 1), np.float32), mesh)

    # End-to-end measurement: every pixel still flows host -> device
    # each step (the reference likewise paid its feed_dict path every
    # step) — but the TPU-native way: K steps per dispatch
    # (train.multistep), raw uint8 on the wire (4x fewer bytes),
    # normalization on device, transfers double-buffered against
    # compute.
    K = 20
    step_k = make_multi_step(
        mesh, preprocess=lambda b: (
            b[0].astype(jax.numpy.float32) / 255.0, b[1]))
    batcher = U8ShardedBatcher(U8Dataset.from_float(train_ds),
                               global_batch, 0, raw=True)
    shardings = stacked_batch_shardings(mesh)

    def host_stacks(it):
        while True:
            xs, ys = zip(*(next(it) for _ in range(K)))
            yield (np.stack(xs), np.stack(ys))

    def place(host):
        return jax.tree_util.tree_map(jax.device_put, host, shardings)

    it = prefetch_with(host_stacks(batcher.forever()), place, size=2)

    # Compile + warmup outside the timed window. One fresh-model step
    # first to capture the initial loss for the learning sanity check.
    state, metrics = step_k(state, next(it))
    initial_loss = float(jax.device_get(metrics["loss"]))
    state, metrics = step_k(state, next(it))
    float(jax.device_get(metrics["loss"]))
    jax.block_until_ready(state.params)

    dispatches = 30
    t0 = time.perf_counter()
    for _ in range(dispatches):
        state, metrics = step_k(state, next(it))
    # Host readback, not just block_until_ready: on tunneled TPU
    # runtimes the latter can return before remote execution finishes,
    # inflating throughput; pulling a scalar that depends on the last
    # step is an honest barrier.
    final_loss = float(jax.device_get(metrics["loss"]))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    # Learning sanity: a degenerate step (NaN loss, dead graph) must not
    # post a throughput number. ~640 Adam steps on an 8k-image synthetic
    # set decisively beats the fresh-model loss.
    assert np.isfinite(final_loss), f"non-finite loss {final_loss}"
    assert final_loss < initial_loss, (
        f"loss did not decrease: {initial_loss} -> {final_loss}")

    steps = dispatches * K
    images_per_sec = steps * global_batch / dt
    per_chip = images_per_sec / n_dev
    record = {
        "metric": "mnist_cnn_train_images_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_AGG_IMAGES_PER_SEC, 2),
        # Effective platform: a CPU-fallback number must never be
        # compared against a TPU trajectory unlabeled.
        "platform": platform,
    }
    # Provenance for the regress ledger: git sha + the calibration
    # profile id in effect (observe.registry.artifact_stamp).
    from tensorflow_distributed_tpu.observe.registry import (
        artifact_stamp, default_calibration_path)
    record.update(artifact_stamp(default_calibration_path()))
    print(json.dumps(record))
    if args.out:
        from tensorflow_distributed_tpu.observe.registry import write_jsonl
        write_jsonl(args.out, [record])


if __name__ == "__main__":
    main()
