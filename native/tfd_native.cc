// Native host-side data runtime for the TPU framework.
//
// The reference's data path is TensorFlow's C++ input pipeline driven
// from Python (SURVEY.md N13/N14: idx.gz parsing in
// tensorflow.examples input_data, then a feed_dict host->runtime copy
// every step). This is our own native equivalent, built for the TPU
// host: the Python layer stays the orchestrator, but byte-level work
// (IDX decode, shuffle, gather, u8->f32 normalize) and the
// double-buffered batch production run here, off the GIL, so the host
// can keep the chips fed.
//
// Exposed as a plain C ABI consumed via ctypes
// (tensorflow_distributed_tpu/native/runtime.py). No Python.h
// dependency — the image has no pybind11 and this keeps the build a
// single g++ invocation.

#include <zlib.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ----------------------------------------------------------- utilities

uint64_t splitmix64(uint64_t* s) {
  uint64_t z = (*s += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

void fisher_yates(int64_t* idx, int64_t n, uint64_t* rng) {
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = static_cast<int64_t>(splitmix64(rng) % (i + 1));
    int64_t t = idx[i];
    idx[i] = idx[j];
    idx[j] = t;
  }
}

void parallel_for(int64_t n, int nthreads,
                  const std::function<void(int64_t, int64_t)>& fn) {
  if (nthreads <= 1 || n < 2 * nthreads) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // namespace

extern "C" {

// ------------------------------------------------------------ IDX read
//
// Reads an IDX file (optionally gzip-compressed — zlib's gzread
// transparently handles both), as written by the MNIST distribution
// the reference downloads (mnist_python_m.py:133). Returns 0 on
// success; caller frees *out_data with tfd_free.
//
// dtype codes from the IDX spec: 0x08 u8, 0x09 i8, 0x0B i16, 0x0C i32,
// 0x0D f32, 0x0E f64.

int tfd_idx_read(const char* path, void** out_data, int64_t* dims,
                 int* out_ndim, int* out_dtype) {
  gzFile f = gzopen(path, "rb");
  if (!f) return -1;
  unsigned char magic[4];
  if (gzread(f, magic, 4) != 4 || magic[0] != 0 || magic[1] != 0) {
    gzclose(f);
    return -2;
  }
  int dtype = magic[2], ndim = magic[3];
  if (ndim < 1 || ndim > 4) {
    gzclose(f);
    return -3;
  }
  static const int sizes[16] = {0, 0, 0, 0, 0, 0, 0, 0,
                                1, 1, 0, 2, 4, 4, 8, 0};
  int esize = (dtype >= 0 && dtype < 16) ? sizes[dtype] : 0;
  if (esize == 0) {
    gzclose(f);
    return -4;
  }
  int64_t total = 1;
  for (int i = 0; i < ndim; ++i) {
    unsigned char b[4];
    if (gzread(f, b, 4) != 4) {
      gzclose(f);
      return -5;
    }
    dims[i] = (int64_t(b[0]) << 24) | (int64_t(b[1]) << 16) |
              (int64_t(b[2]) << 8) | int64_t(b[3]);
    total *= dims[i];
  }
  int64_t nbytes = total * esize;
  unsigned char* buf = static_cast<unsigned char*>(std::malloc(nbytes));
  if (!buf) {
    gzclose(f);
    return -6;
  }
  int64_t got = 0;
  while (got < nbytes) {
    int chunk = static_cast<int>(
        nbytes - got > (1 << 28) ? (1 << 28) : nbytes - got);
    int r = gzread(f, buf + got, chunk);
    if (r <= 0) {
      std::free(buf);
      gzclose(f);
      return -7;
    }
    got += r;
  }
  gzclose(f);
  // IDX multi-byte ints are big-endian; swap only on little-endian
  // hosts (x86/ARM) — a big-endian host must keep the bytes as-is.
  const uint32_t one = 1;
  const bool little_endian =
      *reinterpret_cast<const unsigned char*>(&one) == 1;
  if (esize > 1 && little_endian) {
    unsigned char* p = buf;
    for (int64_t i = 0; i < total; ++i, p += esize) {
      for (int b = 0; b < esize / 2; ++b) {
        unsigned char t = p[b];
        p[b] = p[esize - 1 - b];
        p[esize - 1 - b] = t;
      }
    }
  }
  *out_data = buf;
  *out_ndim = ndim;
  *out_dtype = dtype;
  return 0;
}

void tfd_free(void* p) { std::free(p); }

// --------------------------------------------- threaded gather+convert
//
// out[i, :] = src[idx[i], :] * scale, u8 -> f32, fanned across threads.
// This is the byte-work under the reference's next_batch + feed_dict
// (mnist_python_m.py:291-294) done natively.

void tfd_gather_u8_f32(const uint8_t* src, int64_t item,
                       const int64_t* idx, int64_t n, float scale,
                       float* out, int nthreads) {
  parallel_for(n, nthreads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* s = src + idx[i] * item;
      float* d = out + i * item;
      for (int64_t j = 0; j < item; ++j) d[j] = s[j] * scale;
    }
  });
}

// ------------------------------------------------- prefetch ring buffer
//
// Background producer thread emitting shuffled (x: f32 [B, item],
// y: i32 [B]) batches into a bounded queue — the native analog of the
// double-buffered device feed (data/prefetch.py) on the host side.
// Epochs reshuffle with a per-epoch derived seed; batches never cross
// an epoch boundary (drop_last semantics), matching the sharded
// batcher's contract.

struct Batch {
  std::vector<float> x;
  std::vector<int32_t> y;
};

struct TfdPrefetcher {
  const uint8_t* images;
  const int32_t* labels;
  int64_t n, item, batch;
  int depth, nthreads;
  float scale;
  uint64_t seed;

  std::deque<Batch> queue;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::atomic<bool> stop{false};
  std::thread producer;

  void run() {
    std::vector<int64_t> order(n);
    uint64_t epoch = 0;
    while (!stop.load()) {
      for (int64_t i = 0; i < n; ++i) order[i] = i;
      uint64_t rng = seed + 0x632be59bd9b4e019ULL * (epoch + 1);
      fisher_yates(order.data(), n, &rng);
      for (int64_t off = 0; off + batch <= n && !stop.load();
           off += batch) {
        Batch b;
        b.x.resize(batch * item);
        b.y.resize(batch);
        tfd_gather_u8_f32(images, item, order.data() + off, batch, scale,
                          b.x.data(), nthreads);
        for (int64_t i = 0; i < batch; ++i)
          b.y[i] = labels[order[off + i]];
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] {
          return stop.load() || static_cast<int>(queue.size()) < depth;
        });
        if (stop.load()) return;
        queue.push_back(std::move(b));
        cv_get.notify_one();
      }
      ++epoch;
    }
  }
};

TfdPrefetcher* tfd_prefetch_create(const uint8_t* images,
                                   const int32_t* labels, int64_t n,
                                   int64_t item, int64_t batch, int depth,
                                   uint64_t seed, int nthreads,
                                   float scale) {
  if (batch > n || batch <= 0) return nullptr;
  auto* p = new TfdPrefetcher();
  p->images = images;
  p->labels = labels;
  p->n = n;
  p->item = item;
  p->batch = batch;
  p->depth = depth > 0 ? depth : 2;
  p->nthreads = nthreads > 0 ? nthreads : 1;
  p->scale = scale;
  p->seed = seed;
  p->producer = std::thread([p] { p->run(); });
  return p;
}

int tfd_prefetch_next(TfdPrefetcher* p, float* x, int32_t* y) {
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_get.wait(lk, [&] { return p->stop.load() || !p->queue.empty(); });
  if (p->queue.empty()) return -1;
  Batch b = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_put.notify_one();
  lk.unlock();
  std::memcpy(x, b.x.data(), b.x.size() * sizeof(float));
  std::memcpy(y, b.y.data(), b.y.size() * sizeof(int32_t));
  return 0;
}

void tfd_prefetch_destroy(TfdPrefetcher* p) {
  if (!p) return;
  p->stop.store(true);
  p->cv_put.notify_all();
  p->cv_get.notify_all();
  if (p->producer.joinable()) p->producer.join();
  delete p;
}

}  // extern "C"
